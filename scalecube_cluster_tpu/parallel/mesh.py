"""Device-mesh sharding for the SWIM tick: rows over chips, pmax over ICI.

The reference's "distributed" axis is n independent JVM nodes over TCP
(SURVEY.md §2.5); here the analogous first-class parallelism is
**node-sharded data parallelism**: the ``[N, K]`` per-observer state rows
are sharded across TPU devices on a 1-D ``jax.sharding.Mesh``, the whole
round loop runs inside one ``shard_map``-ped ``lax.scan``, and the only
cross-device traffic is the per-round inbox combine (``lax.pmax`` of the
packed-record contribution buffer — ops/delivery.py) riding ICI.

Multi-host scale-out is the same program on a larger mesh: jax places the
mesh over DCN-connected hosts and the identical collective lowers to
ICI-within-slice / DCN-across-slices.  Nothing in the model code changes —
that is the point of designing delivery as one associative reduction.

Pipelined delivery (the default where supported): because delivery is
"send this round, listen next round", the combine's result is first read
by the FOLLOWING round's body — so the scatter path double-buffers the
contribution and defers each round's pmax into the next scan body
(``_pipelined_rounds``), placing the ICI transfer next to that round's
state-independent draw compute where XLA's latency-hiding scheduler can
overlap them.  Bit-identical to the serial combine (a scheduling change,
not a semantics change — pinned by tests/test_pipelined_delivery.py);
``shard_run(..., pipelined=False)`` keeps the serial path as the
comparison baseline (``bench.py --multichip`` reports the ratio).

Randomness under sharding: each device folds its global row offset into the
per-round key (models/swim.swim_tick), so draws are independent across
devices but the trace is only bit-reproducible for a fixed mesh size (the
single-device trace is the oracle-checked one; sharded runs are validated
statistically and for invariants — tests/test_parallel.py).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from scalecube_cluster_tpu.models import compose, swim
from scalecube_cluster_tpu.parallel import compat

NODE_AXIS = "nodes"


def make_mesh(n_devices: Optional[int] = None, axis_name: str = NODE_AXIS) -> Mesh:
    """1-D device mesh over the first ``n_devices`` available devices.

    Asking for more devices than exist raises instead of silently
    truncating: a silently shrunk mesh would run the whole workload on
    fewer chips and report per-chip numbers for a mesh shape that was
    never built (tests/test_parallel.py pins the error).
    """
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"make_mesh: requested {n_devices} devices but only "
                f"{len(devices)} are available ({[str(d) for d in devices]}); "
                f"a silently truncated mesh would misreport per-chip "
                f"throughput — pass n_devices <= {len(devices)} or None "
                f"for all of them"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def state_sharding(mesh: Mesh) -> NamedSharding:
    """Row sharding for SwimState arrays ([N, ...] split on the node axis)."""
    return NamedSharding(mesh, P(mesh.axis_names[0]))


def _shard_prelude(params: swim.SwimParams, mesh: Mesh):
    """The (axis, n_dev, n_local, state_specs, metric out_specs) every
    sharded run shape derives — hoisted so ``shard_run`` and
    ``shard_run_metered`` share one divisibility check and one spec
    block (the duplication CHANGES.md PR 5 flagged)."""
    axis = mesh.axis_names[0]
    n_dev = mesh.devices.size
    if params.n_members % n_dev != 0:
        raise ValueError(
            f"n_members ({params.n_members}) must divide the mesh size ({n_dev})"
        )
    n_local = params.n_members // n_dev
    state_specs = swim.SwimState(
        status=P(axis), inc=P(axis), spread_until=P(axis),
        suspect_deadline=P(axis), self_inc=P(axis),
        # Delay rings are [D, rows, K]: receiver rows on axis 1.
        inbox_ring=P(None, axis), flag_ring=P(None, axis),
        g_infected=P(axis), g_spread_until=P(axis), g_ring=P(None, axis),
        lhm=P(axis),
        epoch=P(axis),
        # Metadata lanes are observer-row-major like the tables.
        md=P(axis), md_spread=P(axis),
    )
    metric_names = ["alive", "suspect", "dead", "absent", "false_positives",
                    "false_suspicion_onsets", "false_suspect_rounds",
                    "stale_view_rounds",
                    "messages_gossip", "messages_ping",
                    "messages_ping_sent", "messages_ping_req_sent",
                    "refutations"]
    if params.n_user_gossips > 0:
        metric_names.append("user_gossip_infected")
    if params.sync_interval > 0:
        metric_names.append("messages_anti_entropy")
    if params.metadata_keys > 0:
        metric_names.append("metadata_divergent")
    out_metric_specs = {name: P() for name in metric_names}
    return axis, n_dev, n_local, state_specs, out_metric_specs


def _resolve_pipelined(pipelined: Optional[bool], params: swim.SwimParams,
                       world: swim.SwimWorld, n_rounds: int) -> bool:
    """``pipelined=None`` auto-selects: pipeline whenever the config
    supports it (scatter delivery, no delay rings, no seed gate — see
    swim.pipelined_delivery_unsupported_reason) and there is at least
    one round to overlap.  ``True`` insists and raises with the reason
    when unsupported; ``False`` forces the serial combine (the bench's
    comparison baseline)."""
    if pipelined is False:
        return False
    reason = swim.pipelined_delivery_unsupported_reason(params, world)
    if reason is None and n_rounds >= 1:
        return True
    if pipelined:
        raise NotImplementedError(
            f"pipelined delivery: {reason or 'needs n_rounds >= 1'}"
        )
    return False


# The software-pipelined delivery loop lives with the other scan
# drivers in models/compose.py; re-exported under the historical name.
_pipelined_rounds = compose._pipelined_rounds


def _composed_shard_run(base_key, params: swim.SwimParams,
                        world: swim.SwimWorld, n_rounds: int, mesh: Mesh,
                        state, start_round, pipelined, spec):
    """The ONE sharded run body behind :func:`shard_run` and
    :func:`shard_run_metered` (their world-spec / shard_map plumbing
    was the last spec/decode twin block CHANGES.md flagged): resolve
    the prelude + pipeline choice, then hand the per-device row slice
    to the composed plane runner
    (models/compose.composed_shard_scan).  ``spec`` None = no planes
    (shard_run); a MetricsSpec = one sharded MetricsPlane
    (shard_run_metered)."""
    from scalecube_cluster_tpu.telemetry import metrics as tmetrics

    axis, n_dev, n_local, state_specs, out_metric_specs = _shard_prelude(
        params, mesh
    )
    use_pipeline = _resolve_pipelined(pipelined, params, world, n_rounds)
    metered = spec is not None

    if state is None:
        state = swim.initial_state(params, world)
    world_specs = jax.tree.map(lambda _: P(), world)
    ms0 = tmetrics.MetricsState.init(spec) if metered else None
    ms_specs = jax.tree.map(lambda _: P(), ms0) if metered else None

    def sharded_body(base_key, world, state, *ms_args):
        offset = jax.lax.axis_index(axis) * n_local
        planes = ()
        lead = None
        if metered:
            lead = (jax.lax.axis_index(axis) == 0).astype(jnp.int32)
            planes = (tmetrics.MetricsPlane(spec,
                                            metrics_state=ms_args[0]),)
        final_state, results, metrics = compose.composed_shard_scan(
            base_key, params, world, state, n_rounds, start_round,
            offset, axis, n_dev, n_local, planes=planes,
            use_pipeline=use_pipeline, lead=lead,
        )
        if metered:
            return final_state, results["metrics"], metrics
        return final_state, metrics

    in_specs = (P(), world_specs, state_specs) \
        + ((ms_specs,) if metered else ())
    out_specs = ((state_specs, ms_specs, out_metric_specs) if metered
                 else (state_specs, out_metric_specs))
    return compat.shard_map(
        sharded_body,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_replication=False,
    )(base_key, world, state, *((ms0,) if metered else ()))


@partial(jax.jit, static_argnames=("params", "n_rounds", "mesh", "pipelined"))
def shard_run(base_key, params: swim.SwimParams, world: swim.SwimWorld,
              n_rounds: int, mesh: Mesh,
              state: Optional[swim.SwimState] = None, start_round: int = 0,
              pipelined: Optional[bool] = None):
    """models/swim.run, row-sharded over ``mesh``.

    The scan lives *inside* shard_map, so the per-round pmax is the only
    collective XLA emits and the whole n_rounds loop compiles to one
    per-device program.  World arrays ([N] ground truth / fault schedule)
    are replicated — they are O(N) scalars, not O(N·K) state.

    ``pipelined`` (static): ``None`` (default) auto-selects the
    double-buffered delivery pipeline when the config supports it —
    scatter mode's round-r inbox pmax is issued against the carried
    contribution and consumed by round r+1's body, overlapping the ICI
    transfer with the next round's draw compute (``_pipelined_rounds``;
    bit-identical to the serial combine).  ``False`` forces the serial
    in-round combine; ``True`` insists and raises when unsupported.

    Returns (final_state, metrics) with state rows sharded over the mesh
    and metrics replicated (already psum-combined inside the tick).

    Thin alias over the composed plane runner
    (models/compose.composed_shard_scan, via ``_composed_shard_run``);
    the scan body lives there.
    """
    return _composed_shard_run(base_key, params, world, n_rounds, mesh,
                               state, start_round, pipelined, None)


@partial(jax.jit, static_argnames=("params", "n_rounds", "mesh", "spec",
                                   "pipelined"))
def shard_run_metered(base_key, params: swim.SwimParams,
                      world: swim.SwimWorld, n_rounds: int, mesh: Mesh,
                      spec=None, state: Optional[swim.SwimState] = None,
                      start_round: int = 0,
                      pipelined: Optional[bool] = None):
    """``shard_run`` with the health-metrics registry carried per device
    and psum-combined across the mesh before offload
    (telemetry/metrics.py; the combine rides
    ``parallel/compat.psum_tree``, the same seam as the inbox pmax).

    Each device accumulates a LOCAL registry inside the scan: row-local
    signals (suspicion transitions, the lifetime histogram) add on
    every device, while tick counters that are already psum-global
    inside ``swim_tick`` add on the lead device only (the ``lead``
    weight in ``telemetry.metrics.observe_tick``) — so the single
    end-of-run registry psum yields exact global totals with no
    per-round collective beyond what the tick already pays.  Gauges are
    assembled from psum'd numerators and come back replicated.

    ``pipelined``: same contract as :func:`shard_run` — the registry
    plane observes each round after its (deferred) merge with the same
    pre-merge state and round index the serial body sees, so the
    registry totals stay bit-identical too.

    Returns ``(final_state, metrics_state, metrics)`` with the state
    rows sharded, the registry and metrics replicated.

    Thin alias over the composed plane runner (one sharded
    ``telemetry.metrics.MetricsPlane``); the scan body lives there.
    """
    from scalecube_cluster_tpu.telemetry import metrics as tmetrics

    if spec is None:
        spec = tmetrics.MetricsSpec.default()
    return _composed_shard_run(base_key, params, world, n_rounds, mesh,
                               state, start_round, pipelined, spec)
