"""Device-mesh sharding for the SWIM tick: rows over chips, pmax over ICI.

The reference's "distributed" axis is n independent JVM nodes over TCP
(SURVEY.md §2.5); here the analogous first-class parallelism is
**node-sharded data parallelism**: the ``[N, K]`` per-observer state rows
are sharded across TPU devices on a 1-D ``jax.sharding.Mesh``, the whole
round loop runs inside one ``shard_map``-ped ``lax.scan``, and the only
cross-device traffic is the per-round inbox combine (``lax.pmax`` of the
packed-record contribution buffer — ops/delivery.py) riding ICI.

Multi-host scale-out is the same program on a larger mesh: jax places the
mesh over DCN-connected hosts and the identical collective lowers to
ICI-within-slice / DCN-across-slices.  Nothing in the model code changes —
that is the point of designing delivery as one associative reduction.

Randomness under sharding: each device folds its global row offset into the
per-round key (models/swim.swim_tick), so draws are independent across
devices but the trace is only bit-reproducible for a fixed mesh size (the
single-device trace is the oracle-checked one; sharded runs are validated
statistically and for invariants — tests/test_parallel.py).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.parallel import compat

NODE_AXIS = "nodes"


def make_mesh(n_devices: Optional[int] = None, axis_name: str = NODE_AXIS) -> Mesh:
    """1-D device mesh over the first ``n_devices`` available devices."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def state_sharding(mesh: Mesh) -> NamedSharding:
    """Row sharding for SwimState arrays ([N, ...] split on the node axis)."""
    return NamedSharding(mesh, P(mesh.axis_names[0]))


@partial(jax.jit, static_argnames=("params", "n_rounds", "mesh"))
def shard_run(base_key, params: swim.SwimParams, world: swim.SwimWorld,
              n_rounds: int, mesh: Mesh,
              state: Optional[swim.SwimState] = None, start_round: int = 0):
    """models/swim.run, row-sharded over ``mesh``.

    The scan lives *inside* shard_map, so the per-round pmax is the only
    collective XLA emits and the whole n_rounds loop compiles to one
    per-device program.  World arrays ([N] ground truth / fault schedule)
    are replicated — they are O(N) scalars, not O(N·K) state.

    Returns (final_state, metrics) with state rows sharded over the mesh
    and metrics replicated (already psum-combined inside the tick).
    """
    axis = mesh.axis_names[0]
    n_dev = mesh.devices.size
    if params.n_members % n_dev != 0:
        raise ValueError(
            f"n_members ({params.n_members}) must divide the mesh size ({n_dev})"
        )
    n_local = params.n_members // n_dev

    if state is None:
        state = swim.initial_state(params, world)

    state_specs = swim.SwimState(
        status=P(axis), inc=P(axis), spread_until=P(axis),
        suspect_deadline=P(axis), self_inc=P(axis),
        # Delay rings are [D, rows, K]: receiver rows on axis 1.
        inbox_ring=P(None, axis), flag_ring=P(None, axis),
        g_infected=P(axis), g_spread_until=P(axis), g_ring=P(None, axis),
    )
    world_specs = jax.tree.map(lambda _: P(), world)
    metric_spec = P()

    def sharded_body(base_key, world, state):
        offset = jax.lax.axis_index(axis) * n_local

        def body(carry, round_idx):
            return swim.swim_tick(
                carry, round_idx, base_key, params, world,
                offset=offset, axis_name=axis, n_devices=n_dev,
            )

        rounds = jnp.arange(n_rounds, dtype=jnp.int32) + start_round
        return jax.lax.scan(body, state, rounds)

    metric_names = ["alive", "suspect", "dead", "absent", "false_positives",
                    "false_suspicion_onsets", "false_suspect_rounds",
                    "stale_view_rounds",
                    "messages_gossip", "messages_ping",
                    "messages_ping_sent", "messages_ping_req_sent",
                    "refutations"]
    if params.n_user_gossips > 0:
        metric_names.append("user_gossip_infected")
    out_metric_specs = {name: metric_spec for name in metric_names}
    return compat.shard_map(
        sharded_body,
        mesh=mesh,
        in_specs=(P(), world_specs, state_specs),
        out_specs=(state_specs, out_metric_specs),
        check_replication=False,
    )(base_key, world, state)


@partial(jax.jit, static_argnames=("params", "n_rounds", "mesh", "spec"))
def shard_run_metered(base_key, params: swim.SwimParams,
                      world: swim.SwimWorld, n_rounds: int, mesh: Mesh,
                      spec=None, state: Optional[swim.SwimState] = None,
                      start_round: int = 0):
    """``shard_run`` with the health-metrics registry carried per device
    and psum-combined across the mesh before offload
    (telemetry/metrics.py; the combine rides
    ``parallel/compat.psum_tree``, the same seam as the inbox pmax).

    Each device accumulates a LOCAL registry inside the scan: row-local
    signals (suspicion transitions, the lifetime histogram) add on
    every device, while tick counters that are already psum-global
    inside ``swim_tick`` add on the lead device only (the ``lead``
    weight in ``telemetry.metrics.observe_tick``) — so the single
    end-of-run registry psum yields exact global totals with no
    per-round collective beyond what the tick already pays.  Gauges are
    assembled from psum'd numerators and come back replicated.

    Returns ``(final_state, metrics_state, metrics)`` with the state
    rows sharded, the registry and metrics replicated.
    """
    from scalecube_cluster_tpu.telemetry import metrics as tmetrics

    if spec is None:
        spec = tmetrics.MetricsSpec.default()
    axis = mesh.axis_names[0]
    n_dev = mesh.devices.size
    if params.n_members % n_dev != 0:
        raise ValueError(
            f"n_members ({params.n_members}) must divide the mesh size ({n_dev})"
        )
    n_local = params.n_members // n_dev
    kn = swim.Knobs.from_params(params)

    if state is None:
        state = swim.initial_state(params, world)
    ms0 = tmetrics.MetricsState.init(spec)

    state_specs = swim.SwimState(
        status=P(axis), inc=P(axis), spread_until=P(axis),
        suspect_deadline=P(axis), self_inc=P(axis),
        inbox_ring=P(None, axis), flag_ring=P(None, axis),
        g_infected=P(axis), g_spread_until=P(axis), g_ring=P(None, axis),
    )
    world_specs = jax.tree.map(lambda _: P(), world)
    ms_specs = jax.tree.map(lambda _: P(), ms0)

    def sharded_body(base_key, world, state, ms):
        offset = jax.lax.axis_index(axis) * n_local
        lead = (jax.lax.axis_index(axis) == 0).astype(jnp.int32)

        def body(carry, round_idx):
            st, ms = carry
            prev_status = st.status
            prev_deadline, _ = swim._wide_timer_fields(st, params,
                                                       round_idx)
            new_st, m = swim.swim_tick(
                st, round_idx, base_key, params, world,
                offset=offset, axis_name=axis, n_devices=n_dev,
            )
            ms = tmetrics.observe_tick(
                ms, spec, params, kn, round_idx, prev_status,
                prev_deadline, new_st.status, m, world, lead=lead,
            )
            return (new_st, ms), m

        rounds = jnp.arange(n_rounds, dtype=jnp.int32) + start_round
        (final_state, ms), metrics = jax.lax.scan(body, (state, ms),
                                                  rounds)
        end = start_round + n_rounds
        _, spread_wide = swim._wide_timer_fields(final_state, params, end)
        alive_here = jax.lax.dynamic_slice_in_dim(
            world.alive_at(end), offset, n_local
        )
        ms = tmetrics.sample_gauges(
            ms, spec, params, kn, final_state.status, spread_wide,
            alive_here, end, world,
            last_tick_metrics={k: metrics[k][-1]
                               for k in ("messages_gossip",)
                               if k in metrics},
            axis_name=axis,
        )
        ms = tmetrics.aggregate_across_devices(ms, axis)
        return final_state, ms, metrics

    metric_names = ["alive", "suspect", "dead", "absent", "false_positives",
                    "false_suspicion_onsets", "false_suspect_rounds",
                    "stale_view_rounds",
                    "messages_gossip", "messages_ping",
                    "messages_ping_sent", "messages_ping_req_sent",
                    "refutations"]
    if params.n_user_gossips > 0:
        metric_names.append("user_gossip_infected")
    out_metric_specs = {name: P() for name in metric_names}
    return compat.shard_map(
        sharded_body,
        mesh=mesh,
        in_specs=(P(), world_specs, state_specs, ms_specs),
        out_specs=(state_specs, ms_specs, out_metric_specs),
        check_replication=False,
    )(base_key, world, state, ms0)
