"""Device-mesh sharding for the SWIM tick: rows over chips, pmax over ICI.

The reference's "distributed" axis is n independent JVM nodes over TCP
(SURVEY.md §2.5); here the analogous first-class parallelism is
**node-sharded data parallelism**: the ``[N, K]`` per-observer state rows
are sharded across TPU devices on a 1-D ``jax.sharding.Mesh``, the whole
round loop runs inside one ``shard_map``-ped ``lax.scan``, and the only
cross-device traffic is the per-round inbox combine (``lax.pmax`` of the
packed-record contribution buffer — ops/delivery.py) riding ICI.

Multi-host scale-out is the same program on a larger mesh: jax places the
mesh over DCN-connected hosts and the identical collective lowers to
ICI-within-slice / DCN-across-slices.  Nothing in the model code changes —
that is the point of designing delivery as one associative reduction.

Pipelined delivery (the default where supported): because delivery is
"send this round, listen next round", the combine's result is first read
by the FOLLOWING round's body — so the scatter path double-buffers the
contribution and defers each round's pmax into the next scan body
(``_pipelined_rounds``), placing the ICI transfer next to that round's
state-independent draw compute where XLA's latency-hiding scheduler can
overlap them.  Bit-identical to the serial combine (a scheduling change,
not a semantics change — pinned by tests/test_pipelined_delivery.py);
``shard_run(..., pipelined=False)`` keeps the serial path as the
comparison baseline (``bench.py --multichip`` reports the ratio).

Randomness under sharding: each device folds its global row offset into the
per-round key (models/swim.swim_tick), so draws are independent across
devices but the trace is only bit-reproducible for a fixed mesh size (the
single-device trace is the oracle-checked one; sharded runs are validated
statistically and for invariants — tests/test_parallel.py).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.parallel import compat

NODE_AXIS = "nodes"


def make_mesh(n_devices: Optional[int] = None, axis_name: str = NODE_AXIS) -> Mesh:
    """1-D device mesh over the first ``n_devices`` available devices.

    Asking for more devices than exist raises instead of silently
    truncating: a silently shrunk mesh would run the whole workload on
    fewer chips and report per-chip numbers for a mesh shape that was
    never built (tests/test_parallel.py pins the error).
    """
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"make_mesh: requested {n_devices} devices but only "
                f"{len(devices)} are available ({[str(d) for d in devices]}); "
                f"a silently truncated mesh would misreport per-chip "
                f"throughput — pass n_devices <= {len(devices)} or None "
                f"for all of them"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def state_sharding(mesh: Mesh) -> NamedSharding:
    """Row sharding for SwimState arrays ([N, ...] split on the node axis)."""
    return NamedSharding(mesh, P(mesh.axis_names[0]))


def _shard_prelude(params: swim.SwimParams, mesh: Mesh):
    """The (axis, n_dev, n_local, state_specs, metric out_specs) every
    sharded run shape derives — hoisted so ``shard_run`` and
    ``shard_run_metered`` share one divisibility check and one spec
    block (the duplication CHANGES.md PR 5 flagged)."""
    axis = mesh.axis_names[0]
    n_dev = mesh.devices.size
    if params.n_members % n_dev != 0:
        raise ValueError(
            f"n_members ({params.n_members}) must divide the mesh size ({n_dev})"
        )
    n_local = params.n_members // n_dev
    state_specs = swim.SwimState(
        status=P(axis), inc=P(axis), spread_until=P(axis),
        suspect_deadline=P(axis), self_inc=P(axis),
        # Delay rings are [D, rows, K]: receiver rows on axis 1.
        inbox_ring=P(None, axis), flag_ring=P(None, axis),
        g_infected=P(axis), g_spread_until=P(axis), g_ring=P(None, axis),
        lhm=P(axis),
        epoch=P(axis),
    )
    metric_names = ["alive", "suspect", "dead", "absent", "false_positives",
                    "false_suspicion_onsets", "false_suspect_rounds",
                    "stale_view_rounds",
                    "messages_gossip", "messages_ping",
                    "messages_ping_sent", "messages_ping_req_sent",
                    "refutations"]
    if params.n_user_gossips > 0:
        metric_names.append("user_gossip_infected")
    if params.sync_interval > 0:
        metric_names.append("messages_anti_entropy")
    out_metric_specs = {name: P() for name in metric_names}
    return axis, n_dev, n_local, state_specs, out_metric_specs


def _resolve_pipelined(pipelined: Optional[bool], params: swim.SwimParams,
                       world: swim.SwimWorld, n_rounds: int) -> bool:
    """``pipelined=None`` auto-selects: pipeline whenever the config
    supports it (scatter delivery, no delay rings, no seed gate — see
    swim.pipelined_delivery_unsupported_reason) and there is at least
    one round to overlap.  ``True`` insists and raises with the reason
    when unsupported; ``False`` forces the serial combine (the bench's
    comparison baseline)."""
    if pipelined is False:
        return False
    reason = swim.pipelined_delivery_unsupported_reason(params, world)
    if reason is None and n_rounds >= 1:
        return True
    if pipelined:
        raise NotImplementedError(
            f"pipelined delivery: {reason or 'needs n_rounds >= 1'}"
        )
    return False


def _pipelined_rounds(base_key, params: swim.SwimParams,
                      world: swim.SwimWorld, state: swim.SwimState,
                      n_rounds: int, start_round, offset, axis: str,
                      n_dev: int, on_round=None, carry0=None):
    """Software-pipelined scatter round loop (runs INSIDE shard_map).

    Round structure: scan body j combines + merges round j-1's carried
    contribution (swim.swim_tick_recv) and then computes round j's
    sends (swim.swim_tick_send); the first send runs as a prologue and
    the last combine+merge as an epilogue.  The cross-device pmax of a
    round therefore sits in the SAME program body as the next round's
    state-independent draw compute (targets, drop masks, FD chains),
    which is what lets XLA's latency-hiding scheduler run the ICI
    transfer under it — in the serial body the pmax's only in-body
    consumers follow it immediately, and an async collective pair
    cannot span the scan iteration boundary.

    Because delivery is already "send this round, listen next round"
    (the merge is the tick's last phase), this is a scheduling change
    only: outputs are BIT-IDENTICAL to the serial scan
    (tests/test_pipelined_delivery.py), at the cost of double-buffering
    one [N, K] contribution in the carry — a SINGLE packed-key buffer
    under the fused wire (SwimParams.fused_wire, the default: the
    ALIVE flags ride the key bits), the legacy key + int8 flag pair
    under ``fused_wire=False``.

    ``on_round(extra, prev_state, round_idx, new_state, metrics)`` is
    the per-round observation hook (the metered twin's registry fold),
    applied after each round's merge with the round's OWN index and
    pre-merge state — exactly the serial ordering; ``carry0`` is its
    initial value.  Returns (final_state, extra, stacked metrics).
    """
    if n_rounds < 1:
        raise ValueError("pipelined delivery needs n_rounds >= 1")

    def send(st, r):
        return swim.swim_tick_send(st, r, base_key, params, world,
                                   offset=offset, axis_name=axis,
                                   n_devices=n_dev)

    def recv(st, pend, aux, r):
        return swim.swim_tick_recv(st, pend, aux, r, base_key, params,
                                   world, offset=offset, axis_name=axis,
                                   n_devices=n_dev)

    start = jnp.asarray(start_round, jnp.int32)
    pending, send_aux = send(state, start)

    def body(carry, round_idx):
        st, pend, aux, extra = carry
        new_st, metrics = recv(st, pend, aux, round_idx - 1)
        if on_round is not None:
            extra = on_round(extra, st, round_idx - 1, new_st, metrics)
        new_pend, new_aux = send(new_st, round_idx)
        return (new_st, new_pend, new_aux, extra), metrics

    rounds = jnp.arange(1, n_rounds, dtype=jnp.int32) + start
    (st, pend, aux, extra), ms = jax.lax.scan(
        body, (state, pending, send_aux, carry0), rounds
    )
    last = start + jnp.int32(n_rounds - 1)
    final_state, last_metrics = recv(st, pend, aux, last)
    if on_round is not None:
        extra = on_round(extra, st, last, final_state, last_metrics)
    metrics = jax.tree.map(
        lambda rows, tail: jnp.concatenate([rows, tail[None]], axis=0),
        ms, last_metrics,
    )
    return final_state, extra, metrics


@partial(jax.jit, static_argnames=("params", "n_rounds", "mesh", "pipelined"))
def shard_run(base_key, params: swim.SwimParams, world: swim.SwimWorld,
              n_rounds: int, mesh: Mesh,
              state: Optional[swim.SwimState] = None, start_round: int = 0,
              pipelined: Optional[bool] = None):
    """models/swim.run, row-sharded over ``mesh``.

    The scan lives *inside* shard_map, so the per-round pmax is the only
    collective XLA emits and the whole n_rounds loop compiles to one
    per-device program.  World arrays ([N] ground truth / fault schedule)
    are replicated — they are O(N) scalars, not O(N·K) state.

    ``pipelined`` (static): ``None`` (default) auto-selects the
    double-buffered delivery pipeline when the config supports it —
    scatter mode's round-r inbox pmax is issued against the carried
    contribution and consumed by round r+1's body, overlapping the ICI
    transfer with the next round's draw compute (``_pipelined_rounds``;
    bit-identical to the serial combine).  ``False`` forces the serial
    in-round combine; ``True`` insists and raises when unsupported.

    Returns (final_state, metrics) with state rows sharded over the mesh
    and metrics replicated (already psum-combined inside the tick).
    """
    axis, n_dev, n_local, state_specs, out_metric_specs = _shard_prelude(
        params, mesh
    )
    use_pipeline = _resolve_pipelined(pipelined, params, world, n_rounds)

    if state is None:
        state = swim.initial_state(params, world)
    world_specs = jax.tree.map(lambda _: P(), world)

    def sharded_body(base_key, world, state):
        offset = jax.lax.axis_index(axis) * n_local

        if use_pipeline:
            final_state, _, metrics = _pipelined_rounds(
                base_key, params, world, state, n_rounds, start_round,
                offset, axis, n_dev,
            )
            return final_state, metrics

        def body(carry, round_idx):
            return swim.swim_tick(
                carry, round_idx, base_key, params, world,
                offset=offset, axis_name=axis, n_devices=n_dev,
            )

        # _fused_scan honors params.rounds_per_step (bit-identical for
        # any K; k == 1 is the classic per-round scan) — the pipelined
        # path declares fusion unsupported instead
        # (swim.pipelined_delivery_unsupported_reason), so auto-select
        # falls back to this body when both knobs are on.
        return swim._fused_scan(body, state, n_rounds, start_round,
                                params.rounds_per_step)

    return compat.shard_map(
        sharded_body,
        mesh=mesh,
        in_specs=(P(), world_specs, state_specs),
        out_specs=(state_specs, out_metric_specs),
        check_replication=False,
    )(base_key, world, state)


@partial(jax.jit, static_argnames=("params", "n_rounds", "mesh", "spec",
                                   "pipelined"))
def shard_run_metered(base_key, params: swim.SwimParams,
                      world: swim.SwimWorld, n_rounds: int, mesh: Mesh,
                      spec=None, state: Optional[swim.SwimState] = None,
                      start_round: int = 0,
                      pipelined: Optional[bool] = None):
    """``shard_run`` with the health-metrics registry carried per device
    and psum-combined across the mesh before offload
    (telemetry/metrics.py; the combine rides
    ``parallel/compat.psum_tree``, the same seam as the inbox pmax).

    Each device accumulates a LOCAL registry inside the scan: row-local
    signals (suspicion transitions, the lifetime histogram) add on
    every device, while tick counters that are already psum-global
    inside ``swim_tick`` add on the lead device only (the ``lead``
    weight in ``telemetry.metrics.observe_tick``) — so the single
    end-of-run registry psum yields exact global totals with no
    per-round collective beyond what the tick already pays.  Gauges are
    assembled from psum'd numerators and come back replicated.

    ``pipelined``: same contract as :func:`shard_run` — the registry
    hook observes each round after its (deferred) merge with the same
    pre-merge state and round index the serial body sees, so the
    registry totals stay bit-identical too.

    Returns ``(final_state, metrics_state, metrics)`` with the state
    rows sharded, the registry and metrics replicated.
    """
    from scalecube_cluster_tpu.telemetry import metrics as tmetrics

    if spec is None:
        spec = tmetrics.MetricsSpec.default()
    axis, n_dev, n_local, state_specs, out_metric_specs = _shard_prelude(
        params, mesh
    )
    use_pipeline = _resolve_pipelined(pipelined, params, world, n_rounds)
    kn = swim.Knobs.from_params(params)

    if state is None:
        state = swim.initial_state(params, world)
    ms0 = tmetrics.MetricsState.init(spec)

    world_specs = jax.tree.map(lambda _: P(), world)
    ms_specs = jax.tree.map(lambda _: P(), ms0)

    def sharded_body(base_key, world, state, ms):
        offset = jax.lax.axis_index(axis) * n_local
        lead = (jax.lax.axis_index(axis) == 0).astype(jnp.int32)

        def observe(ms, prev_st, round_idx, new_st, m):
            prev_deadline, _ = swim._wide_timer_fields(prev_st, params,
                                                       round_idx)
            return tmetrics.observe_tick(
                ms, spec, params, kn, round_idx, prev_st.status,
                prev_deadline, new_st.status, m, world, lead=lead,
            )

        if use_pipeline:
            final_state, ms, metrics = _pipelined_rounds(
                base_key, params, world, state, n_rounds, start_round,
                offset, axis, n_dev, on_round=observe, carry0=ms,
            )
        else:
            def body(carry, round_idx):
                st, ms = carry
                new_st, m = swim.swim_tick(
                    st, round_idx, base_key, params, world,
                    offset=offset, axis_name=axis, n_devices=n_dev,
                )
                ms = observe(ms, st, round_idx, new_st, m)
                return (new_st, ms), m

            # rounds_per_step rides the same _fused_scan as the
            # unmetered body (bit-identical for any K).
            (final_state, ms), metrics = swim._fused_scan(
                body, (state, ms), n_rounds, start_round,
                params.rounds_per_step,
            )
        end = start_round + n_rounds
        _, spread_wide = swim._wide_timer_fields(final_state, params, end)
        alive_here = jax.lax.dynamic_slice_in_dim(
            world.alive_at(end), offset, n_local
        )
        ms = tmetrics.sample_gauges(
            ms, spec, params, kn, final_state.status, spread_wide,
            alive_here, end, world,
            last_tick_metrics={k: metrics[k][-1]
                               for k in ("messages_gossip",)
                               if k in metrics},
            axis_name=axis,
            lhm=final_state.lhm if params.lhm_max > 0 else None,
        )
        ms = tmetrics.aggregate_across_devices(ms, axis)
        return final_state, ms, metrics

    return compat.shard_map(
        sharded_body,
        mesh=mesh,
        in_specs=(P(), world_specs, state_specs, ms_specs),
        out_specs=(state_specs, ms_specs, out_metric_specs),
        check_replication=False,
    )(base_key, world, state, ms0)
