"""shard_map compatibility: one resolution point for every JAX vintage.

``shard_map`` has lived at three addresses across the JAX versions this
repo meets in the wild: ``jax.experimental.shard_map.shard_map`` (the
original, replication-checking kwarg ``check_rep``), ``jax.shard_map``
(promoted to the public namespace, kwarg renamed ``check_vma``), and —
on trimmed builds — nowhere at all.  Resolving the symbol lazily at
call sites meant every caller re-discovered the difference (and the
tests died with ``AttributeError`` at run time on older installs), so
this module resolves it ONCE at import:

  - :data:`HAS_SHARD_MAP` — whether any implementation exists; test
    modules that need sharding skip cleanly on it instead of erroring.
  - :func:`shard_map` — the unified wrapper.  Call it with the mesh /
    in_specs / out_specs keywords and the version-neutral
    ``check_replication`` flag; the wrapper forwards to whichever
    kwarg spelling the installed implementation takes.

Nothing else in the repo should touch ``jax.shard_map`` or
``jax.experimental.shard_map`` directly.
"""

from __future__ import annotations

import inspect

import jax


def _resolve():
    """(callable-or-None, replication-kwarg-name-or-None), chosen once.

    Prefers the public ``jax.shard_map`` when present (the experimental
    module is deleted in the versions that have it), else the
    experimental location.  The replication-check kwarg is discovered
    from the signature rather than hard-coded per location, so an
    implementation that renames it again degrades to "don't pass it"
    instead of a TypeError.
    """
    fn = getattr(jax, "shard_map", None)
    if fn is None:
        try:
            from jax.experimental.shard_map import shard_map as fn
        except Exception:  # pragma: no cover — trimmed build
            return None, None
    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):  # pragma: no cover — C-level signature
        params = {}
    for name in ("check_vma", "check_rep"):
        if name in params:
            return fn, name
    return fn, None


_SHARD_MAP, _CHECK_KWARG = _resolve()

HAS_SHARD_MAP = _SHARD_MAP is not None

# Human-readable origin for skip messages / diagnostics.
SHARD_MAP_ORIGIN = (
    None if _SHARD_MAP is None
    else ("jax.shard_map" if _SHARD_MAP is getattr(jax, "shard_map", None)
          else "jax.experimental.shard_map.shard_map")
)

SKIP_REASON = ("no shard_map implementation in this JAX build "
               "(neither jax.shard_map nor jax.experimental.shard_map)")

# The legacy experimental implementation lowers each in-scan psum to its
# own all-reduce; the public one (check_vma era) lowers to the combined
# collectives the traffic byte model pins.  HLO-pinning tests assert the
# modern lowering only — semantics are identical either way.
MODERN_LOWERING = _CHECK_KWARG == "check_vma"
LEGACY_LOWERING_REASON = (
    f"HLO collective pinning assumes the public jax.shard_map lowering; "
    f"this build resolves to {SHARD_MAP_ORIGIN}, whose legacy lowering "
    f"emits per-psum all-reduces"
)


def psum_tree(tree, axis_name):
    """``lax.psum`` every leaf of a pytree over ``axis_name``; identity
    when ``axis_name`` is None (the single-device path).

    The one mesh-reduction helper additive telemetry shares (the
    health-metrics registry psums its counters/histograms across the
    mesh before offload — telemetry/metrics.aggregate_across_devices),
    kept here so multichip aggregation has a single resolution point
    next to the shard_map shim it always rides under.
    """
    if axis_name is None:
        return tree
    return jax.tree_util.tree_map(
        lambda x: jax.lax.psum(x, axis_name), tree
    )


def shard_map(f, *, mesh, in_specs, out_specs, check_replication=False):
    """Version-neutral ``shard_map`` (module docstring).

    ``check_replication`` maps onto ``check_vma`` / ``check_rep`` —
    whichever the installed implementation spells it as.
    """
    if _SHARD_MAP is None:
        raise NotImplementedError(SKIP_REASON)
    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    if _CHECK_KWARG is not None:
        kwargs[_CHECK_KWARG] = check_replication
    return _SHARD_MAP(f, **kwargs)
