"""parallel subpackage of scalecube_cluster_tpu."""
