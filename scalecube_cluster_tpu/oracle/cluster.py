"""The Cluster facade: join/leave, messaging, gossip, metadata, events.

Mirror of the reference's public API surface
(cluster/src/main/java/io/scalecube/cluster/Cluster.java:16-271 and
ClusterImpl.java:85-155 ``join0`` wiring): one call constructs and wires
transport + failure detector + gossip + metadata + membership, starts them
in the reference's order, and exposes the user-facing operations with
system messages filtered out of ``listen``/``listen_gossips``
(ClusterImpl.java:44-58, 202-216).
"""

from __future__ import annotations

import collections

from typing import Callable, Dict, List, Optional

from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.oracle.core import (
    Address,
    CorrelationIdGenerator,
    Member,
    SimFuture,
    Simulator,
    generate_member_id,
)
from scalecube_cluster_tpu.oracle import fdetector as fd_mod
from scalecube_cluster_tpu.oracle import gossip as gossip_mod
from scalecube_cluster_tpu.oracle import membership as mem_mod
from scalecube_cluster_tpu.oracle import metadata as meta_mod
from scalecube_cluster_tpu.oracle.fdetector import FailureDetector
from scalecube_cluster_tpu.oracle.gossip import GossipProtocol
from scalecube_cluster_tpu.oracle.membership import MembershipEvent, MembershipProtocol
from scalecube_cluster_tpu.oracle.metadata import MetadataStore
from scalecube_cluster_tpu.oracle.transport import Message, NetworkEmulator, Transport
from scalecube_cluster_tpu.records import MemberStatus

# System qualifiers hidden from user listen() (ClusterImpl.java:44-58).
SYSTEM_MESSAGES = frozenset(
    {
        fd_mod.PING,
        fd_mod.PING_REQ,
        fd_mod.PING_ACK,
        mem_mod.SYNC,
        mem_mod.SYNC_ACK,
        gossip_mod.GOSSIP_REQ,
        meta_mod.GET_METADATA_REQ,
        meta_mod.GET_METADATA_RESP,
    }
)
SYSTEM_GOSSIPS = frozenset({mem_mod.MEMBERSHIP_GOSSIP})


class Cluster:
    """One simulated cluster member with the full protocol stack.

    Usage mirrors the reference facade::

        sim = Simulator(seed=1)
        alice = Cluster.join(sim)                     # seedless bootstrap
        bob = Cluster.join(sim, seeds=[alice.address])
        sim.run_for(2_000)                            # virtual ms
        assert bob.other_members() == [alice.member()]
    """

    def __init__(self, sim: Simulator, config: ClusterConfig, alias: Optional[str] = None):
        self.sim = sim
        self.config = config
        self.transport = Transport(
            sim,
            address=None if config.port == 0 else Address("localhost", config.port),
            max_frame_length=config.max_frame_length,
        )
        member_id = generate_member_id(sim.rng) if alias is None else alias
        # memberHost/memberPort override: the member ADVERTISES a different
        # address than the transport bind (ClusterImpl.createLocalMember
        # honoring TransportConfig.memberHost/memberPort; exercised by
        # MembershipProtocolTest.java:464-535).  The advertised address is
        # aliased to the same transport so peers can reach it.
        if config.member_host is not None:
            advertised = Address(
                config.member_host,
                config.member_port or self.transport.address.port,
            )
            self.transport.add_alias(advertised)
        else:
            advertised = self.transport.address
        self.local_member = Member(member_id, advertised)
        cid_generator = CorrelationIdGenerator(member_id)

        # Component construction + wiring (ClusterImpl.join0, :85-155).
        self.failure_detector = FailureDetector(
            self.local_member, self.transport, config, sim, cid_generator
        )
        self.gossip = GossipProtocol(self.local_member, self.transport, config, sim)
        self.metadata_store = MetadataStore(
            self.local_member, self.transport, config.metadata_dict(), config, sim, cid_generator
        )
        self.membership = MembershipProtocol(
            self.local_member,
            self.transport,
            self.failure_detector,
            self.gossip,
            self.metadata_store,
            config,
            sim,
            cid_generator,
        )
        # Membership events feed FD's and gossip's peer lists
        # (ClusterImpl.java:103-118).
        self.membership.listen(self.failure_detector.on_member_event)
        self.membership.listen(self.gossip.on_member_event)

        # Removal ring buffer for the monitor snapshot (the JMX MBean keeps
        # the last 42 removals, MembershipProtocolImpl.java:695-703).
        self._removals = collections.deque(maxlen=42)
        self.membership.listen(
            lambda e: self._removals.append((sim.now, e.member))
            if e.is_removed() else None
        )

        self._shutdown = False
        self.on_joined: SimFuture = SimFuture()

    # -- join --------------------------------------------------------------

    @staticmethod
    def join(
        sim: Simulator,
        seeds: Optional[List[Address]] = None,
        config: Optional[ClusterConfig] = None,
        metadata: Optional[Dict[str, str]] = None,
        alias: Optional[str] = None,
    ) -> "Cluster":
        """Construct, wire, and start a member (Cluster.java:19-87 factories)."""
        config = config or ClusterConfig.default_local()
        if seeds is not None:
            config = config.replace(seed_members=tuple(str(a) for a in seeds))
        if metadata is not None:
            config = config.replace(metadata=tuple(metadata.items()))
        cluster = Cluster(sim, config, alias=alias)
        cluster._start()
        return cluster

    def _start(self) -> None:
        # Start order mirrors join0: FD, gossip, metadata serve, membership
        # initial sync (ClusterImpl.java:139-155).
        self.failure_detector.start()
        self.gossip.start()
        self.metadata_store.start()
        self.membership.start().subscribe(self.on_joined.resolve, self.on_joined.reject)

    # -- identity / views --------------------------------------------------

    @property
    def address(self) -> Address:
        return self.transport.address

    def member(self) -> Member:
        return self.local_member

    def members(self) -> List[Member]:
        return self.membership.member_list()

    def other_members(self) -> List[Member]:
        return self.membership.other_members()

    def member_by_id(self, member_id: str) -> Optional[Member]:
        return self.membership.member_by_id(member_id)

    def member_by_address(self, address: Address) -> Optional[Member]:
        return self.membership.member_by_address(address)

    # -- messaging (ClusterImpl.java:180-216) ------------------------------

    def send(self, target, message: Message) -> SimFuture:
        address = target.address if isinstance(target, Member) else target
        return self.transport.send(address, message)

    def request_response(self, target, request: Message, timeout_ms: float = 3_000) -> SimFuture:
        address = target.address if isinstance(target, Member) else target
        return self.transport.request_response(request, address, timeout_ms)

    def listen(self, handler: Callable[[Message], None]) -> None:
        """User messages only — system qualifiers filtered (ClusterImpl.java:202-205)."""
        self.transport.listen(
            lambda msg: handler(msg) if msg.qualifier not in SYSTEM_MESSAGES else None
        )

    # -- gossip (ClusterImpl.java:207-216) ---------------------------------

    def spread_gossip(self, message: Message) -> SimFuture:
        return self.gossip.spread(message)

    def listen_gossips(self, handler: Callable[[Message], None]) -> None:
        self.gossip.listen(
            lambda msg: handler(msg) if msg.qualifier not in SYSTEM_GOSSIPS else None
        )

    # -- metadata (ClusterImpl.java:228-280) -------------------------------

    def metadata(self, member: Optional[Member] = None) -> Optional[Dict[str, str]]:
        return self.metadata_store.metadata(member)

    def update_metadata(self, metadata: Dict[str, str]) -> SimFuture:
        """Replace local metadata and bump incarnation so peers re-fetch."""
        self.metadata_store.update_metadata(metadata)
        return self.membership.update_incarnation()

    def update_metadata_property(self, key: str, value: str) -> SimFuture:
        metadata = dict(self.metadata_store.metadata() or {})
        metadata[key] = value
        return self.update_metadata(metadata)

    def remove_metadata_property(self, key: str) -> SimFuture:
        metadata = dict(self.metadata_store.metadata() or {})
        metadata.pop(key, None)
        return self.update_metadata(metadata)

    # -- membership events (ClusterImpl.java:283-293) ----------------------

    def monitor(self) -> Dict[str, object]:
        """Queryable state snapshot — the JMX MBean analog.

        Mirrors ClusterImpl.JmxMonitorMBean + MembershipProtocolImpl's
        MBean surface (ClusterImpl.java:366-396,
        MembershipProtocolImpl.java:693-749): incarnation, member id,
        alive/suspected member lists, the last-42-removals ring, and the
        metadata dump.
        """
        records = self.membership.membership_records()
        return {
            "member": str(self.local_member),
            "incarnation": self.membership.incarnation,
            "alive_members": sorted(
                str(r.member) for r in records
                if r.status == MemberStatus.ALIVE
            ),
            "suspected_members": sorted(
                str(r.member) for r in records
                if r.status == MemberStatus.SUSPECT
            ),
            "removed_members": [
                {"at_ms": t, "member": str(m)} for t, m in self._removals
            ],
            "metadata": dict(self.metadata_store.metadata() or {}),
        }

    def listen_membership(self, handler: Callable[[MembershipEvent], None]) -> None:
        """Prepends synthetic ADDED for already-known members, then live events."""
        for member in self.other_members():
            handler(MembershipEvent.added(member, self.metadata(member)))
        self.membership.listen(handler)

    def listen_trace(self, handler: Callable) -> None:
        """Raw membership-table transition stream (the numeric schema
        shared with the dense tick's event trace —
        ``MembershipProtocol.listen_trace``; adapt with
        ``telemetry.events.OracleTraceCollector``).  No synthetic
        prefix: the trace starts at subscription time."""
        self.membership.listen_trace(handler)

    # -- shutdown (ClusterImpl.java:297-347) -------------------------------

    def shutdown(self) -> SimFuture:
        """Graceful leave: spread DEAD gossip, wait for its sweep, then stop."""
        done = SimFuture()
        if self._shutdown:
            done.resolve(None)
            return done
        self._shutdown = True

        def dispose(_=None):
            self.metadata_store.stop()
            self.membership.stop()
            self.gossip.stop()
            self.failure_detector.stop()
            self.transport.stop()
            done.resolve(None)

        self.membership.leave_cluster().subscribe(dispose, lambda _err: dispose())
        return done

    @property
    def is_shutdown(self) -> bool:
        return self._shutdown

    # -- fault injection (ClusterImpl.java:360-363) ------------------------

    @property
    def network_emulator(self) -> NetworkEmulator:
        return self.transport.network_emulator
