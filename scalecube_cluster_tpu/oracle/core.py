"""Discrete-event simulation core: virtual clock, futures, addresses, members.

The reference runs each node's whole protocol stack on one dedicated Reactor
scheduler thread with wall-clock timers (ClusterImpl.java:93,
``Schedulers.newSingle``), which makes tests slow and unseeded-flaky
(SURVEY.md §4 weaknesses).  The oracle inverts both choices deliberately:
**virtual time** (a heapq event loop, so simulated minutes cost milliseconds)
and **one seeded PRNG** (bit-reproducible runs).  Everything else mirrors the
reference's single-threaded-per-node execution model: callbacks run one at a
time in deterministic (time, seq) order, so protocol logic needs no locks,
exactly like the reference's L3 (SURVEY.md §1 concurrency model).
"""

from __future__ import annotations

import dataclasses
import hashlib
import heapq
import random
from typing import Any, Callable, Dict, List, Optional, Tuple


@dataclasses.dataclass(frozen=True, order=True)
class Address:
    """host:port endpoint identity (reference: transport/Address.java:10-142)."""

    host: str
    port: int

    @staticmethod
    def from_string(s: str) -> "Address":
        host, sep, port = s.rpartition(":")
        if not sep or not host:
            raise ValueError(f"can't parse address from string: {s!r}")
        return Address(host, int(port))

    def __str__(self) -> str:
        return f"{self.host}:{self.port}"


@dataclasses.dataclass(frozen=True)
class Member:
    """Cluster member identity: random id + address (reference: cluster/Member.java)."""

    id: str
    address: Address

    def __str__(self) -> str:
        return f"{self.id}@{self.address}"


def generate_member_id(rng: random.Random) -> str:
    """10 random bytes -> MD5 -> hex (reference: membership/IdGenerator.java:21-54)."""
    raw = bytes(rng.getrandbits(8) for _ in range(10))
    return hashlib.md5(raw).hexdigest()


class CorrelationIdGenerator:
    """``memberId-counter`` correlation ids (reference: CorrelationIdGenerator.java:6-17)."""

    def __init__(self, prefix: str):
        self._prefix = prefix
        self._counter = 0

    def next_cid(self) -> str:
        self._counter += 1
        return f"{self._prefix}-{self._counter}"


class Timer:
    """Cancellable scheduled task handle (the oracle's reactor ``Disposable``)."""

    __slots__ = ("cancelled", "fn")

    def __init__(self, fn: Callable[[], None]):
        self.fn = fn
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True

    # reactor-style alias used by protocol code ported from Disposable.dispose()
    dispose = cancel

    @property
    def is_disposed(self) -> bool:
        return self.cancelled


class SimFuture:
    """Single-value async result with success/error callbacks and sim-time timeout.

    Stands in for the reference's ``Mono`` in request-response and spread()
    plumbing.  Callbacks fire synchronously inside the event loop tick.
    """

    __slots__ = ("_done", "_value", "_error", "_callbacks")

    def __init__(self):
        self._done = False
        self._value = None
        self._error: Optional[Exception] = None
        self._callbacks: List[Tuple[Callable, Optional[Callable]]] = []

    @property
    def done(self) -> bool:
        return self._done

    @property
    def value(self):
        if not self._done:
            raise RuntimeError("future not resolved")
        if self._error is not None:
            raise self._error
        return self._value

    @property
    def error(self) -> Optional[Exception]:
        return self._error if self._done else None

    def resolve(self, value=None) -> None:
        if self._done:
            return
        self._done, self._value = True, value
        for on_ok, _ in self._callbacks:
            if on_ok is not None:
                on_ok(value)
        self._callbacks.clear()

    def reject(self, error: Exception) -> None:
        if self._done:
            return
        self._done, self._error = True, error
        for _, on_err in self._callbacks:
            if on_err is not None:
                on_err(error)
        self._callbacks.clear()

    def subscribe(self, on_ok: Optional[Callable] = None, on_err: Optional[Callable] = None) -> None:
        if self._done:
            if self._error is None:
                if on_ok is not None:
                    on_ok(self._value)
            elif on_err is not None:
                on_err(self._error)
            return
        self._callbacks.append((on_ok, on_err))


class TimeoutError_(Exception):
    """Virtual-time timeout (the oracle's ``java.util.concurrent.TimeoutException``)."""


class Simulator:
    """The event loop: virtual clock + seeded PRNG + transport registry.

    One Simulator hosts many in-process nodes — the oracle analog of the
    reference's "multi-node is multi-instance in-JVM" test harness
    (SURVEY.md §4), with the wall clock replaced by ``now`` and every random
    draw routed through ``rng``.
    """

    def __init__(self, seed: int = 0):
        self.now: float = 0.0
        self.rng = random.Random(seed)
        self._queue: List[Tuple[float, int, Timer]] = []
        self._seq = 0
        # address -> bound transport (set by transport.bind/stop)
        self.transports: Dict[Address, Any] = {}
        self._next_ephemeral_port = 40000

    # -- ports -------------------------------------------------------------

    def allocate_port(self) -> int:
        """Ephemeral port allocation (reference binds port 0, TransportConfig.java:5)."""
        port = self._next_ephemeral_port
        self._next_ephemeral_port += 1
        return port

    # -- scheduling --------------------------------------------------------

    def schedule(self, delay_ms: float, fn: Callable[[], None]) -> Timer:
        """One-shot task after ``delay_ms`` of virtual time."""
        timer = Timer(fn)
        self._seq += 1
        heapq.heappush(self._queue, (self.now + max(0.0, delay_ms), self._seq, timer))
        return timer

    def schedule_periodic(self, interval_ms: float, fn: Callable[[], None]) -> Timer:
        """Fixed-rate periodic task, first run after one interval
        (matches ``scheduler.schedulePeriodically(fn, interval, interval)``
        call sites, e.g. FailureDetectorImpl.java:102-107)."""
        handle = Timer(lambda: None)

        def tick():
            if handle.cancelled:
                return
            fn()
            if not handle.cancelled:
                self.schedule(interval_ms, tick)

        self.schedule(interval_ms, tick)
        return handle

    def timeout_future(self, future: SimFuture, timeout_ms: float) -> SimFuture:
        """Reject ``future`` with TimeoutError_ after ``timeout_ms`` unless done."""
        self.schedule(timeout_ms, lambda: future.reject(TimeoutError_(f"timeout {timeout_ms}ms")))
        return future

    # -- running -----------------------------------------------------------

    def run_until(self, t_ms: float) -> None:
        """Process events with time <= t_ms; advance the clock to t_ms."""
        while self._queue and self._queue[0][0] <= t_ms:
            when, _, timer = heapq.heappop(self._queue)
            self.now = max(self.now, when)
            if not timer.cancelled:
                timer.fn()
        self.now = max(self.now, t_ms)

    def run_for(self, dt_ms: float) -> None:
        self.run_until(self.now + dt_ms)
