"""Member metadata store with remote pull (oracle form).

Behavior-for-behavior port of the reference
(cluster/src/main/java/io/scalecube/cluster/metadata/MetadataStoreImpl.java:22-242):
per-member KV maps, local CRUD, and remote fetch via request-response
(``sc/metadata/req``/``resp``).  Metadata is never gossiped — only the
owner's incarnation bump is, and observers then pull directly
(SURVEY.md §2.1 row 5).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from scalecube_cluster_tpu.oracle.core import (
    CorrelationIdGenerator,
    Member,
    SimFuture,
    Simulator,
)
from scalecube_cluster_tpu.oracle.transport import Message, Transport

# Qualifiers (MetadataStoreImpl.java:28-29).
GET_METADATA_REQ = "sc/metadata/req"
GET_METADATA_RESP = "sc/metadata/resp"


class GetMetadataRequest:
    """Target-member payload (reference: metadata/GetMetadataRequest.java)."""

    def __init__(self, member: Member):
        self.member = member


class GetMetadataResponse:
    """Owner + metadata payload (reference: metadata/GetMetadataResponse.java)."""

    def __init__(self, member: Member, metadata: Dict[str, str]):
        self.member = member
        self.metadata = metadata


class MetadataStore:
    """One node's metadata component."""

    def __init__(
        self,
        local_member: Member,
        transport: Transport,
        metadata: Dict[str, str],
        config,  # needs .metadata_timeout
        sim: Simulator,
        cid_generator: CorrelationIdGenerator,
    ):
        self.local_member = local_member
        self.transport = transport
        self.config = config
        self.sim = sim
        self.cid_generator = cid_generator
        self.members_metadata: Dict[Member, Dict[str, str]] = {}
        self._stopped = False
        self._unsubscribe: Optional[Callable] = None
        self.update_metadata(dict(metadata))

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Serve GET_METADATA_REQ (MetadataStoreImpl.java:77-85)."""
        self._unsubscribe = self.transport.listen(self._on_message)

    def stop(self) -> None:
        self._stopped = True
        if self._unsubscribe is not None:
            self._unsubscribe()
        self.members_metadata.clear()

    # -- local CRUD (MetadataStoreImpl.java:96-146) ------------------------

    def metadata(self, member: Optional[Member] = None) -> Optional[Dict[str, str]]:
        return self.members_metadata.get(member or self.local_member)

    def update_metadata(self, metadata: Dict[str, str]) -> Optional[Dict[str, str]]:
        return self.update_metadata_for(self.local_member, metadata)

    def update_metadata_for(self, member: Member, metadata: Dict[str, str]) -> Optional[Dict[str, str]]:
        previous = self.members_metadata.get(member)
        self.members_metadata[member] = dict(metadata)
        return previous

    def remove_metadata(self, member: Member) -> Optional[Dict[str, str]]:
        if member == self.local_member:
            raise ValueError("remove_metadata must not accept local member")
        return self.members_metadata.pop(member, None)

    # -- remote fetch (MetadataStoreImpl.java:149-186) ---------------------

    def fetch_metadata(self, member: Member) -> SimFuture:
        if member == self.local_member:
            future = SimFuture()
            future.resolve(dict(self.members_metadata.get(member, {})))
            return future
        cid = self.cid_generator.next_cid()
        request = Message(
            qualifier=GET_METADATA_REQ,
            correlation_id=cid,
            data=GetMetadataRequest(member),
        )
        result = SimFuture()
        self.transport.request_response(
            request, member.address, timeout_ms=self.config.metadata_timeout
        ).subscribe(
            lambda response: result.resolve(dict(response.data.metadata)),
            result.reject,
        )
        return result

    # -- serving (MetadataStoreImpl.java:202-241) --------------------------

    def _on_message(self, message: Message) -> None:
        if self._stopped or message.qualifier != GET_METADATA_REQ:
            return
        target = message.data.member
        if target.id != self.local_member.id:
            return  # request for a previous owner of this address
        response = Message(
            qualifier=GET_METADATA_RESP,
            correlation_id=message.correlation_id,
            data=GetMetadataResponse(self.local_member, dict(self.metadata() or {})),
        )
        self.transport.send(message.sender, response)
