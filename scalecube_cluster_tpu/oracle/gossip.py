"""Infection-style gossip dissemination (oracle form).

Behavior-for-behavior port of the reference
(cluster/src/main/java/io/scalecube/cluster/gossip/GossipProtocolImpl.java:31-327):
spread() enqueues with id ``memberId-counter`` and resolves when the gossip
is swept; each period the node picks a fanout-sized window over a shuffled
member list, sends each live gossip (one GOSSIP_REQ message per gossip) to
targets not already known infected, and sweeps gossips older than
``2*(periodsToSpread+1)`` periods.  Delivery dedups by gossip id.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Set

from scalecube_cluster_tpu import swim_math
from scalecube_cluster_tpu.oracle.core import Member, SimFuture, Simulator
from scalecube_cluster_tpu.oracle.transport import Message, Transport

# Qualifier (GossipProtocolImpl.java:37).
GOSSIP_REQ = "sc/gossip/req"


@dataclasses.dataclass(frozen=True)
class Gossip:
    """gossip id + payload message (reference: gossip/Gossip.java:1-49)."""

    gossip_id: str
    message: Message


@dataclasses.dataclass(frozen=True)
class GossipRequest:
    """One gossip + sender member id (reference: gossip/GossipRequest.java:1-37)."""

    gossips: tuple  # tuple[Gossip, ...]
    from_id: str


class GossipState:
    """Local per-gossip state (reference: gossip/GossipState.java:8-38)."""

    __slots__ = ("gossip", "infection_period", "infected")

    def __init__(self, gossip: Gossip, infection_period: int):
        self.gossip = gossip
        self.infection_period = infection_period
        # member ids this gossip was received from (so we skip resending to them)
        self.infected: Set[str] = set()


class GossipProtocol:
    """One node's gossip component."""

    def __init__(
        self,
        local_member: Member,
        transport: Transport,
        config,  # GossipConfig view of ClusterConfig
        sim: Simulator,
    ):
        self.local_member = local_member
        self.transport = transport
        self.config = config
        self.sim = sim

        self.current_period = 0
        self.gossip_counter = 0
        self.gossips: Dict[str, GossipState] = {}
        self.futures: Dict[str, SimFuture] = {}
        # Shuffled-window target selection state (GossipProtocolImpl.java:52-53).
        self.remote_members: List[Member] = []
        self.remote_members_index = -1

        self._listeners: List[Callable[[Message], None]] = []
        self._stopped = False
        self._periodic = None
        self._unsubscribe = transport.listen(self._on_message)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Begin periodic spreading (GossipProtocolImpl.java:105-112)."""
        self._periodic = self.sim.schedule_periodic(
            self.config.gossip_interval, self._do_spread_gossip
        )

    def stop(self) -> None:
        self._stopped = True
        if self._periodic is not None:
            self._periodic.cancel()
        self._unsubscribe()
        self._listeners.clear()

    def listen(self, handler: Callable[[Message], None]) -> None:
        """Subscribe to first-delivery of remote gossips (deduped by id)."""
        self._listeners.append(handler)

    # -- membership feed (GossipProtocolImpl.java:185-193) -----------------

    def on_member_event(self, event) -> None:
        member = event.member
        if event.is_removed() and member in self.remote_members:
            self.remote_members.remove(member)
        if event.is_added():
            self.remote_members.append(member)

    # -- API ---------------------------------------------------------------

    def spread(self, message: Message) -> SimFuture:
        """Enqueue a gossip; future resolves with the gossip id on sweep
        (GossipProtocolImpl.java:124-128,163-169)."""
        gossip = Gossip(self._generate_gossip_id(), message)
        self.gossips[gossip.gossip_id] = GossipState(gossip, self.current_period)
        future = SimFuture()
        self.futures[gossip.gossip_id] = future
        return future

    # -- periodic tick (GossipProtocolImpl.java:139-157) -------------------

    def _do_spread_gossip(self) -> None:
        if self._stopped:
            return
        period = self.current_period
        self.current_period += 1
        if not self.gossips:
            return
        for member in self._select_gossip_members():
            self._spread_gossips_to(period, member)
        self._sweep_gossips(period)

    # -- handlers (GossipProtocolImpl.java:171-183) ------------------------

    def _on_message(self, message: Message) -> None:
        if self._stopped or message.qualifier != GOSSIP_REQ:
            return
        period = self.current_period
        request: GossipRequest = message.data
        for gossip in request.gossips:
            state = self.gossips.get(gossip.gossip_id)
            if state is None:  # new gossip: store + first-delivery emit
                state = GossipState(gossip, period)
                self.gossips[gossip.gossip_id] = state
                for handler in list(self._listeners):
                    handler(gossip.message)
            state.infected.add(request.from_id)

    # -- helpers (GossipProtocolImpl.java:239-308) -------------------------

    def _generate_gossip_id(self) -> str:
        gid = f"{self.local_member.id}-{self.gossip_counter}"
        self.gossip_counter += 1
        return gid

    def _select_gossips_to_send(self, period: int, member: Member) -> List[Gossip]:
        periods_to_spread = swim_math.gossip_periods_to_spread(
            self.config.gossip_repeat_mult, len(self.remote_members) + 1
        )
        return [
            state.gossip
            for state in self.gossips.values()
            if state.infection_period + periods_to_spread >= period
            and member.id not in state.infected
        ]

    def _select_gossip_members(self) -> List[Member]:
        fanout = self.config.gossip_fanout
        if len(self.remote_members) < fanout:
            return list(self.remote_members)
        # Shuffled sliding window, reshuffle at wrap (GossipProtocolImpl.java:252-273).
        if self.remote_members_index < 0 or self.remote_members_index + fanout > len(
            self.remote_members
        ):
            self.sim.rng.shuffle(self.remote_members)
            self.remote_members_index = 0
        selected = self.remote_members[self.remote_members_index : self.remote_members_index + fanout]
        self.remote_members_index += fanout
        return selected

    def _spread_gossips_to(self, period: int, member: Member) -> None:
        # One GOSSIP_REQ message per gossip (GossipProtocolImpl.java:211-237).
        for gossip in self._select_gossips_to_send(period, member):
            msg = Message(
                qualifier=GOSSIP_REQ,
                data=GossipRequest((gossip,), self.local_member.id),
            )
            self.transport.send(member.address, msg)

    def _sweep_gossips(self, period: int) -> None:
        periods_to_sweep = swim_math.gossip_periods_to_sweep(
            self.config.gossip_repeat_mult, len(self.remote_members) + 1
        )
        to_remove = [
            state
            for state in self.gossips.values()
            if period > state.infection_period + periods_to_sweep
        ]
        for state in to_remove:
            del self.gossips[state.gossip.gossip_id]
            future = self.futures.pop(state.gossip.gossip_id, None)
            if future is not None:
                future.resolve(state.gossip.gossip_id)
