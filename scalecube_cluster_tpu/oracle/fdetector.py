"""SWIM random-probe failure detector (oracle form).

Behavior-for-behavior port of the reference
(cluster/src/main/java/io/scalecube/cluster/fdetector/FailureDetectorImpl.java:28-389):
periodic direct PING with timeout, k-proxy PING_REQ rescue with the
remaining-time budget, transit ping/ack relaying, per-period ALIVE/SUSPECT
verdict events.  All timers and random draws go through the simulator.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional

from scalecube_cluster_tpu.oracle.core import (
    CorrelationIdGenerator,
    Member,
    Simulator,
)
from scalecube_cluster_tpu.oracle.transport import Message, Transport
from scalecube_cluster_tpu.records import MemberStatus

# Qualifiers (FailureDetectorImpl.java:34-36).
PING = "sc/fdetector/ping"
PING_REQ = "sc/fdetector/pingReq"
PING_ACK = "sc/fdetector/pingAck"


@dataclasses.dataclass(frozen=True)
class PingData:
    """Ping payload: issuer, target, optional original issuer for transit pings
    (reference: fdetector/PingData.java:1-50)."""

    from_: Member
    to: Member
    original_issuer: Optional[Member] = None


@dataclasses.dataclass(frozen=True)
class FailureDetectorEvent:
    """Per-period verdict (reference: fdetector/FailureDetectorEvent.java:1-29)."""

    member: Member
    status: MemberStatus


class FailureDetector:
    """One node's failure detector component."""

    def __init__(
        self,
        local_member: Member,
        transport: Transport,
        config,  # FailureDetectorConfig view of ClusterConfig
        sim: Simulator,
        cid_generator: CorrelationIdGenerator,
    ):
        self.local_member = local_member
        self.transport = transport
        self.config = config
        self.sim = sim
        self.cid_generator = cid_generator

        self.current_period = 0
        # Shuffled round-robin probe list (FailureDetectorImpl.java:48-49).
        self.ping_members: List[Member] = []
        self.ping_member_index = 0

        self._listeners: List[Callable[[FailureDetectorEvent], None]] = []
        self._stopped = False
        self._periodic = None
        self._unsubscribe = transport.listen(self._on_message)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Begin periodic probing (FailureDetectorImpl.java:101-108)."""
        self._periodic = self.sim.schedule_periodic(self.config.ping_interval, self._do_ping)

    def stop(self) -> None:
        self._stopped = True
        if self._periodic is not None:
            self._periodic.cancel()
        self._unsubscribe()
        self._listeners.clear()

    def listen(self, handler: Callable[[FailureDetectorEvent], None]) -> None:
        self._listeners.append(handler)

    # -- membership feed (FailureDetectorImpl.java:321-332) ----------------

    def on_member_event(self, event) -> None:
        member = event.member
        if event.is_removed():
            if member in self.ping_members:
                self.ping_members.remove(member)
        if event.is_added():
            # Insert at a random position to decorrelate probe orders.
            size = len(self.ping_members)
            index = self.sim.rng.randrange(size) if size > 0 else 0
            self.ping_members.insert(index, member)

    # -- probe tick (FailureDetectorImpl.java:128-213) ---------------------

    def _do_ping(self) -> None:
        if self._stopped:
            return
        period = self.current_period
        self.current_period += 1

        ping_member = self._select_ping_member()
        if ping_member is None:
            return

        cid = self.cid_generator.next_cid()
        ping_msg = Message(
            qualifier=PING,
            correlation_id=cid,
            data=PingData(self.local_member, ping_member),
        )
        self.transport.request_response(
            ping_msg, ping_member.address, timeout_ms=self.config.ping_timeout
        ).subscribe(
            lambda _msg: self._publish(period, ping_member, MemberStatus.ALIVE),
            lambda _err: self._on_ping_timeout(period, ping_member, cid),
        )

    def _on_ping_timeout(self, period: int, ping_member: Member, cid: str) -> None:
        if self._stopped:
            return
        time_left = self.config.ping_interval - self.config.ping_timeout
        ping_req_members = self._select_ping_req_members(ping_member)
        if time_left <= 0 or not ping_req_members:
            self._publish(period, ping_member, MemberStatus.SUSPECT)
            return
        # PING_REQ to each proxy; each proxy result publishes independently,
        # exactly like the reference's per-proxy subscriptions
        # (FailureDetectorImpl.java:178-213) — membership dedups repeats.
        ping_req_msg = Message(
            qualifier=PING_REQ,
            correlation_id=cid,
            data=PingData(self.local_member, ping_member),
        )
        for proxy in ping_req_members:
            self.transport.request_response(
                ping_req_msg, proxy.address, timeout_ms=time_left
            ).subscribe(
                lambda _msg, m=ping_member: self._publish(period, m, MemberStatus.ALIVE),
                lambda _err, m=ping_member: self._publish(period, m, MemberStatus.SUSPECT),
            )

    # -- message handlers (FailureDetectorImpl.java:217-315) ---------------

    def _on_message(self, message: Message) -> None:
        if self._stopped:
            return
        if message.qualifier == PING:
            self._on_ping(message)
        elif message.qualifier == PING_REQ:
            self._on_ping_req(message)
        elif message.qualifier == PING_ACK and message.data.original_issuer is not None:
            self._on_transit_ping_ack(message)

    def _on_ping(self, message: Message) -> None:
        """Answer PING with PING_ACK — drops pings addressed to a previous
        incarnation of this endpoint (FailureDetectorImpl.java:230-255)."""
        data: PingData = message.data
        if data.to.id != self.local_member.id:
            return
        ack = Message(qualifier=PING_ACK, correlation_id=message.correlation_id, data=data)
        self.transport.send(data.from_.address, ack)

    def _on_ping_req(self, message: Message) -> None:
        """Relay a transit PING on behalf of the original issuer
        (FailureDetectorImpl.java:258-284)."""
        data: PingData = message.data
        transit = Message(
            qualifier=PING,
            correlation_id=message.correlation_id,
            data=PingData(self.local_member, data.to, original_issuer=data.from_),
        )
        self.transport.send(data.to.address, transit)

    def _on_transit_ping_ack(self, message: Message) -> None:
        """Convert a transit ack back to a plain ack for the original issuer
        (FailureDetectorImpl.java:290-315)."""
        data: PingData = message.data
        issuer = data.original_issuer
        ack = Message(
            qualifier=PING_ACK,
            correlation_id=message.correlation_id,
            data=PingData(issuer, data.to),
        )
        self.transport.send(issuer.address, ack)

    # -- selection (FailureDetectorImpl.java:338-361) ----------------------

    def _select_ping_member(self) -> Optional[Member]:
        if not self.ping_members:
            return None
        if self.ping_member_index >= len(self.ping_members):
            self.ping_member_index = 0
            self.sim.rng.shuffle(self.ping_members)
        member = self.ping_members[self.ping_member_index]
        self.ping_member_index += 1
        return member

    def _select_ping_req_members(self, ping_member: Member) -> List[Member]:
        if self.config.ping_req_members <= 0:
            return []
        candidates = [m for m in self.ping_members if m != ping_member]
        if not candidates:
            return []
        self.sim.rng.shuffle(candidates)
        return candidates[: self.config.ping_req_members]

    # -- events ------------------------------------------------------------

    def _publish(self, period: int, member: Member, status: MemberStatus) -> None:
        if self._stopped:
            return
        event = FailureDetectorEvent(member, status)
        for handler in list(self._listeners):
            handler(event)
