"""Message serialization: the MessageCodec SPI and the default JSON codec.

The reference makes every message cross a real wire: MessageCodec
(transport/src/main/java/io/scalecube/transport/MessageCodec.java:9-27) is
the pluggable seam, JacksonMessageCodec
(transport/JacksonMessageCodec.java:15-52) the default — JSON with
default-typing so polymorphic payloads (PingData, SyncData, GossipRequest,
metadata requests) round-trip.  The oracle is in-process, so without a
codec it would quietly pass live Python objects — a capability gap the
round-1 review flagged.  This module restores the seam:

  - :class:`MessageCodec`: serialize/deserialize interface;
  - :class:`JsonMessageCodec`: tagged-JSON default covering every payload
    type in the 9-qualifier wire protocol (SURVEY.md §2.1) plus plain
    JSON-able user data;
  - the oracle Transport routes every send through the configured codec
    (encode → decode, the in-process stand-in for encode → TCP → decode),
    so any unserializable payload fails loudly, exactly like the
    reference's wire (GossipRequestTest.java:40-69 is the model test).

The dense tick's analog is ops/delivery.pack_record/unpack_record — the
record <-> int32 sort-key packing IS the TPU wire format; this module is
the oracle/API-layer counterpart for full messages.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

from scalecube_cluster_tpu.oracle.core import Address, Member
from scalecube_cluster_tpu.records import MemberStatus


class MessageCodec:
    """Serialization SPI (reference: transport/MessageCodec.java:9-27)."""

    def serialize(self, message) -> bytes:
        raise NotImplementedError

    def deserialize(self, payload: bytes):
        raise NotImplementedError


class CodecError(Exception):
    pass


class JsonMessageCodec(MessageCodec):
    """Tagged-JSON codec for Message + all protocol payload types.

    Mirrors JacksonMessageCodec's default-typing: every non-primitive value
    is encoded as ``{"@type": <registered name>, ...fields}`` so payloads
    reconstruct polymorphically (transport/JacksonMessageCodec.java:41-52).
    """

    def __init__(self):
        # Late imports to avoid cycles (these modules import transport,
        # which imports nothing from here at module level).
        from scalecube_cluster_tpu.oracle import transport as tmod
        from scalecube_cluster_tpu.oracle import membership as mmod
        from scalecube_cluster_tpu.oracle import gossip as gmod
        from scalecube_cluster_tpu.oracle import fdetector as fmod
        from scalecube_cluster_tpu.oracle import metadata as dmod

        self._types = {
            "Message": tmod.Message,
            "Address": Address,
            "Member": Member,
            "MembershipRecord": mmod.MembershipRecord,
            "SyncData": mmod.SyncData,
            "PingData": fmod.PingData,
            "Gossip": gmod.Gossip,
            "GossipRequest": gmod.GossipRequest,
            "GetMetadataRequest": dmod.GetMetadataRequest,
            "GetMetadataResponse": dmod.GetMetadataResponse,
        }
        self._names = {cls: name for name, cls in self._types.items()}

    # -- encode -----------------------------------------------------------

    def _enc(self, value: Any):
        # MemberStatus first: it is an IntEnum, so the primitive check
        # below would silently flatten it to a bare int.
        if isinstance(value, MemberStatus):
            return {"@type": "MemberStatus", "value": int(value)}
        if value is None or isinstance(value, (bool, int, float, str)):
            return value
        if isinstance(value, list):
            return [self._enc(v) for v in value]
        if isinstance(value, tuple):
            return {"@type": "tuple", "items": [self._enc(v) for v in value]}
        if isinstance(value, dict):
            return {"@type": "dict",
                    "items": [[self._enc(k), self._enc(v)]
                              for k, v in value.items()]}
        cls = type(value)
        name = self._names.get(cls)
        if name is None:
            raise CodecError(f"unserializable payload type: {cls.__name__}")
        if dataclasses.is_dataclass(value):
            fields = {
                f.name: self._enc(getattr(value, f.name))
                for f in dataclasses.fields(value)
            }
        else:  # plain-attribute payloads (metadata request/response)
            fields = {
                k: self._enc(v) for k, v in vars(value).items()
            }
        return {"@type": name, **fields}

    # -- decode -----------------------------------------------------------

    def _dec(self, value: Any):
        if isinstance(value, list):
            return [self._dec(v) for v in value]
        if not isinstance(value, dict):
            return value
        tag = value.get("@type")
        if tag == "tuple":
            return tuple(self._dec(v) for v in value["items"])
        if tag == "dict":
            return {self._dec(k): self._dec(v) for k, v in value["items"]}
        if tag == "MemberStatus":
            return MemberStatus(value["value"])
        cls = self._types.get(tag)
        if cls is None:
            raise CodecError(f"unknown payload tag: {tag!r}")
        fields = {k: self._dec(v) for k, v in value.items() if k != "@type"}
        return cls(**fields)

    # -- SPI --------------------------------------------------------------

    def serialize(self, message) -> bytes:
        try:
            return json.dumps(self._enc(message)).encode()
        except (TypeError, ValueError) as e:
            raise CodecError(str(e)) from e

    def deserialize(self, payload: bytes):
        return self._dec(json.loads(payload.decode()))
