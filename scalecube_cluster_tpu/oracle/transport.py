"""In-process transport with fault injection: the oracle's wire layer.

Mirrors the reference Transport SPI
(transport/src/main/java/io/scalecube/transport/Transport.java:74-135):
``address / send / request_response / listen / stop / network_emulator`` —
with sockets replaced by direct delivery through the simulator's event loop.
The NetworkEmulator (transport/NetworkEmulator.java:21-273,
NetworkLinkSettings.java:15-80) is ported behavior-for-behavior: per-link
loss%% / exponential mean delay, block = 100%% loss, sent/lost counters; it
sits in the send path exactly where the reference hooks ``tryFail`` then
``tryDelay`` (TransportImpl.java:257-269).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Dict, List, Optional

from scalecube_cluster_tpu.oracle.core import (
    Address,
    SimFuture,
    Simulator,
)


@dataclasses.dataclass(frozen=True)
class Message:
    """Header map + data + sender (reference: transport/Message.java:11-248).

    The reference keeps qualifier/correlationId in a headers map
    (Message.java:17-30 ``q``/``cid``); the oracle promotes the two
    load-bearing headers to fields.
    """

    qualifier: Optional[str] = None
    correlation_id: Optional[str] = None
    data: Any = None
    sender: Optional[Address] = None

    def with_sender(self, sender: Address) -> "Message":
        return dataclasses.replace(self, sender=sender)


class NetworkLinkSettings:
    """Per-link loss%% and mean delay (reference: NetworkLinkSettings.java:15-80)."""

    def __init__(self, loss_percent: int, mean_delay_ms: int):
        self.loss_percent = loss_percent
        self.mean_delay_ms = mean_delay_ms

    def evaluate_loss(self, rng) -> bool:
        """Bernoulli loss draw (NetworkLinkSettings.java:54-57)."""
        return self.loss_percent > 0 and (
            self.loss_percent >= 100 or rng.random() * 100 <= self.loss_percent
        )

    def evaluate_delay(self, rng) -> float:
        """Exponential delay ``-ln(1-U) * mean`` (NetworkLinkSettings.java:64-74)."""
        if self.mean_delay_ms <= 0:
            return 0.0
        u = rng.random()
        return -math.log(1.0 - (1.0 - 1e-10) * u, math.e) * self.mean_delay_ms


DEAD_LINK_SETTINGS = NetworkLinkSettings(100, 0)
ALIVE_LINK_SETTINGS = NetworkLinkSettings(0, 0)


class NetworkEmulatorError(Exception):
    """Raised (to error callbacks) when the emulator drops a message
    (reference: transport/NetworkEmulatorException.java)."""


class FrameTooLongError(Exception):
    """Serialized message exceeds the transport's max frame length.

    The analog of netty's TooLongFrameException under the reference's
    4-byte length-prefix framing: TransportImpl caps frames at
    ``TransportConfig.maxFrameLength`` (2MB default) on both the encode
    and decode paths (transport/TransportImpl.java:370-384,
    TransportConfig.java:9)."""


class NetworkEmulator:
    """Outbound fault injection for one node (reference: NetworkEmulator.java:21-273)."""

    def __init__(self, address: Address, enabled: bool = True):
        self.address = address
        self.enabled = enabled
        self.default_link_settings = ALIVE_LINK_SETTINGS
        self.custom_link_settings: Dict[Address, NetworkLinkSettings] = {}
        self.total_message_sent_count = 0
        self.total_message_lost_count = 0

    def link_settings(self, destination: Address) -> NetworkLinkSettings:
        return self.custom_link_settings.get(destination, self.default_link_settings)

    def set_link_settings(self, destination: Address, loss_percent: int, mean_delay_ms: int) -> None:
        if not self.enabled:
            return
        self.custom_link_settings[destination] = NetworkLinkSettings(loss_percent, mean_delay_ms)

    def set_default_link_settings(self, loss_percent: int, mean_delay_ms: int) -> None:
        if not self.enabled:
            return
        self.default_link_settings = NetworkLinkSettings(loss_percent, mean_delay_ms)

    def block(self, *destinations: Address) -> None:
        """100%% loss toward destinations (NetworkEmulator.java:132-160)."""
        if not self.enabled:
            return
        for destination in self._flatten(destinations):
            self.custom_link_settings[destination] = DEAD_LINK_SETTINGS

    def unblock(self, *destinations: Address) -> None:
        """Remove per-link overrides (NetworkEmulator.java:162-186)."""
        if not self.enabled:
            return
        for destination in self._flatten(destinations):
            self.custom_link_settings.pop(destination, None)

    def unblock_all(self) -> None:
        if not self.enabled:
            return
        self.custom_link_settings.clear()

    @staticmethod
    def _flatten(destinations) -> List[Address]:
        out: List[Address] = []
        for d in destinations:
            if isinstance(d, Address):
                out.append(d)
            else:
                out.extend(d)
        return out


class Transport:
    """In-process point-to-point messaging bound to a simulator.

    Reference parity notes (TransportImpl.java:45-385):
      - ``send`` is fire-and-forget; delivery errors go to the returned
        future's error callback and are otherwise dropped (:257-269);
      - ``request_response`` = send + first inbound message with equal
        correlationId (:205-232) — matched on the shared inbound stream, so
        correlated replies ALSO reach ``listen`` subscribers, which
        membership relies on for SYNC_ACK routing
        (MembershipProtocolImpl.java:320-331);
      - sending to an unbound address fails like a refused TCP connect;
      - a stopped transport delivers nothing (:175-186).
    """

    def __init__(self, sim: Simulator, address: Optional[Address] = None,
                 enabled_emulator: bool = True, codec="json",
                 max_frame_length: Optional[int] = None):
        """``codec``: "json" (default) routes every send through the
        JsonMessageCodec wire round-trip (the in-process analog of the
        reference's encode -> TCP -> decode, JacksonMessageCodec.java:15-52);
        a MessageCodec instance plugs in a custom codec; None disables
        serialization (raw object hand-off).

        ``max_frame_length``: cap on the serialized frame size in bytes
        (TransportConfig.maxFrameLength, 2MB default); an oversized send
        fails its future with :class:`FrameTooLongError` before reaching
        the emulator, like the reference's length-prefix framing
        (TransportImpl.java:370-384).  None = the 2MB default; enforced
        only when a codec is active (no codec = no wire, nothing to
        frame)."""
        from scalecube_cluster_tpu.config import DEFAULT_MAX_FRAME_LENGTH
        self.max_frame_length = (DEFAULT_MAX_FRAME_LENGTH
                                 if max_frame_length is None
                                 else max_frame_length)
        self.sim = sim
        self.address = address or Address("localhost", sim.allocate_port())
        if self.address in sim.transports:
            raise RuntimeError(f"address already in use: {self.address}")
        if codec == "json":
            from scalecube_cluster_tpu.oracle.codec import JsonMessageCodec
            codec = JsonMessageCodec()
        self.codec = codec
        self.network_emulator = NetworkEmulator(self.address, enabled_emulator)
        self._listeners: List[Callable[[Message], None]] = []
        # cid -> pending request-response futures.  A list, not a single slot:
        # the FD's PING_REQ rescue issues one request per proxy all sharing the
        # original ping's cid (FailureDetectorImpl.java:178-213), and the
        # reference resolves every one of them from the shared inbound stream
        # (TransportImpl.java:205-232).
        self._pending: Dict[str, List[SimFuture]] = {}
        self.stopped = False
        self._bound_addresses: List[Address] = [self.address]
        sim.transports[self.address] = self

    def add_alias(self, address: Address) -> None:
        """Bind an additional advertised address to this transport (the
        memberHost/memberPort override seam, TransportConfig.java:107-110).
        Collides like a real bind; unregistered on stop()."""
        if address in self.sim.transports:
            raise RuntimeError(f"address already in use: {address}")
        self.sim.transports[address] = self
        self._bound_addresses.append(address)

    # -- SPI ---------------------------------------------------------------

    def listen(self, handler: Callable[[Message], None]) -> Callable[[], None]:
        """Subscribe to all inbound messages; returns an unsubscribe fn."""
        self._listeners.append(handler)
        return lambda: self._listeners.remove(handler) if handler in self._listeners else None

    def send(self, destination: Address, message: Message) -> SimFuture:
        """Fire-and-forget send through the network emulator."""
        future = SimFuture()
        if self.stopped:
            future.reject(RuntimeError("transport stopped"))
            return future
        message = message.with_sender(self.address)
        if self.codec is not None:
            # The wire: serialize before the emulator hook, deserialize at
            # delivery — unserializable payloads fail the send future, like
            # a codec error inside TransportImpl.send0 (:257-269).
            try:
                frame = self.codec.serialize(message)
                if len(frame) > self.max_frame_length:
                    raise FrameTooLongError(
                        f"frame of {len(frame)} bytes exceeds "
                        f"max_frame_length={self.max_frame_length} "
                        f"({self.address} -> {destination})"
                    )
                message = self.codec.deserialize(frame)
            except Exception as e:  # noqa: BLE001 — surfaced on the future
                future.reject(e)
                return future

        # NetworkEmulator hook: tryFail then tryDelay (TransportImpl.java:257-269).
        settings = self.network_emulator.link_settings(destination)
        self.network_emulator.total_message_sent_count += 1
        if settings.evaluate_loss(self.sim.rng):
            self.network_emulator.total_message_lost_count += 1
            future.reject(NetworkEmulatorError(f"emulator dropped {self.address}->{destination}"))
            return future
        delay = settings.evaluate_delay(self.sim.rng)

        def deliver():
            target = self.sim.transports.get(destination)
            if target is None or target.stopped:
                # Connect refused — reference evicts the cached connection and
                # reports the error to the send future (TransportImpl.java:283-307).
                future.reject(ConnectionError(f"no transport bound at {destination}"))
                return
            future.resolve(None)
            target._on_inbound(message)

        self.sim.schedule(delay, deliver)
        return future

    def request_response(self, message: Message, destination: Address, timeout_ms: float) -> SimFuture:
        """Send + await first inbound message with the same correlation id."""
        cid = message.correlation_id
        if cid is None:
            raise ValueError("request_response requires a correlation id")
        future = SimFuture()
        self._pending.setdefault(cid, []).append(future)

        def cleanup(_ignored):
            futures = self._pending.get(cid)
            if futures is not None:
                if future in futures:
                    futures.remove(future)
                if not futures:
                    del self._pending[cid]

        future.subscribe(cleanup, cleanup)
        self.send(destination, message).subscribe(None, future.reject)
        self.sim.timeout_future(future, timeout_ms)
        return future

    def stop(self) -> None:
        """Unbind; in-flight messages to this address are dropped (like closed sockets)."""
        if self.stopped:
            return
        self.stopped = True
        for bound in self._bound_addresses:
            self.sim.transports.pop(bound, None)
        self._listeners.clear()
        for futures in list(self._pending.values()):
            for future in list(futures):
                future.reject(RuntimeError("transport stopped"))
        self._pending.clear()

    # -- inbound -----------------------------------------------------------

    def _on_inbound(self, message: Message) -> None:
        if self.stopped:
            return
        # Correlated reply resolves EVERY pending request-response future with
        # that cid (shared-inbound-stream matching, TransportImpl.java:205-232)...
        cid = message.correlation_id
        if cid is not None and cid in self._pending:
            for future in list(self._pending.get(cid, ())):
                future.resolve(message)
        # ...and the message still reaches every listen() subscriber (shared
        # inbound stream, TransportImpl.java:205-232).
        for handler in list(self._listeners):
            handler(message)
