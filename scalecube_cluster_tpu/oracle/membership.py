"""SWIM membership protocol with SYNC anti-entropy (oracle form).

Behavior-for-behavior port of the reference
(cluster/src/main/java/io/scalecube/cluster/membership/MembershipProtocolImpl.java:50-750):
the membership table, the five-source merge funnel gated by
``is_overrides``, incarnation self-refutation, suspicion timeouts,
periodic + initial SYNC, leave, and ADDED/REMOVED/UPDATED event emission
with metadata fetch.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, Dict, List, Optional, Tuple

from scalecube_cluster_tpu import swim_math
from scalecube_cluster_tpu.oracle.core import (
    CorrelationIdGenerator,
    Member,
    SimFuture,
    Simulator,
    Timer,
)
from scalecube_cluster_tpu.oracle.fdetector import FailureDetector, FailureDetectorEvent
from scalecube_cluster_tpu.oracle.gossip import GossipProtocol
from scalecube_cluster_tpu.oracle.transport import Address, Message, Transport
from scalecube_cluster_tpu.records import MemberStatus, is_overrides
from scalecube_cluster_tpu.telemetry.events import TraceEventType

# Qualifiers (MembershipProtocolImpl.java:64-66).
SYNC = "sc/membership/sync"
SYNC_ACK = "sc/membership/syncAck"
MEMBERSHIP_GOSSIP = "sc/membership/gossip"

ALIVE = MemberStatus.ALIVE
SUSPECT = MemberStatus.SUSPECT
DEAD = MemberStatus.DEAD
ABSENT = MemberStatus.ABSENT


class UpdateReason(enum.Enum):
    """The five merge sources (MembershipProtocolImpl.java:54-60)."""

    FAILURE_DETECTOR_EVENT = "fd"
    MEMBERSHIP_GOSSIP = "gossip"
    SYNC = "sync"
    INITIAL_SYNC = "initial_sync"
    SUSPICION_TIMEOUT = "suspicion_timeout"


@dataclasses.dataclass(frozen=True)
class MembershipRecord:
    """member + status + incarnation (reference: membership/MembershipRecord.java:12-26)."""

    member: Member
    status: MemberStatus
    incarnation: int

    def is_overrides(self, r0: Optional["MembershipRecord"]) -> bool:
        old_status = int(r0.status) if r0 is not None else int(ABSENT)
        old_inc = r0.incarnation if r0 is not None else 0
        return is_overrides(int(self.status), self.incarnation, old_status, old_inc)


@dataclasses.dataclass(frozen=True)
class SyncData:
    """Full-table payload of SYNC/SYNC_ACK (reference: membership/SyncData.java)."""

    membership: Tuple[MembershipRecord, ...]
    sync_group: str


class EventType(enum.Enum):
    ADDED = "added"
    REMOVED = "removed"
    UPDATED = "updated"


@dataclasses.dataclass(frozen=True)
class MembershipEvent:
    """ADDED/REMOVED/UPDATED notification (reference: membership/MembershipEvent.java:1-123)."""

    type: EventType
    member: Member
    old_metadata: Optional[Dict[str, str]] = None
    new_metadata: Optional[Dict[str, str]] = None

    def is_added(self) -> bool:
        return self.type == EventType.ADDED

    def is_removed(self) -> bool:
        return self.type == EventType.REMOVED

    def is_updated(self) -> bool:
        return self.type == EventType.UPDATED

    @staticmethod
    def added(member: Member, metadata) -> "MembershipEvent":
        return MembershipEvent(EventType.ADDED, member, None, metadata)

    @staticmethod
    def removed(member: Member, metadata) -> "MembershipEvent":
        return MembershipEvent(EventType.REMOVED, member, metadata, None)

    @staticmethod
    def updated(member: Member, old, new) -> "MembershipEvent":
        return MembershipEvent(EventType.UPDATED, member, old, new)


class MembershipProtocol:
    """One node's membership component (SWIM state machine + SYNC)."""

    def __init__(
        self,
        local_member: Member,
        transport: Transport,
        failure_detector: FailureDetector,
        gossip_protocol: GossipProtocol,
        metadata_store,
        config,  # MembershipConfig view of ClusterConfig
        sim: Simulator,
        cid_generator: CorrelationIdGenerator,
    ):
        self.local_member = local_member
        self.transport = transport
        self.failure_detector = failure_detector
        self.gossip_protocol = gossip_protocol
        self.metadata_store = metadata_store
        self.config = config
        self.sim = sim
        self.cid_generator = cid_generator

        # Seeds: dedup, drop own address (MembershipProtocolImpl.java:160-167).
        seen = []
        for addr in config.seed_members:
            address = Address.from_string(addr) if isinstance(addr, str) else addr
            if address not in seen and address != local_member.address and address != transport.address:
                seen.append(address)
        self.seed_members: List[Address] = seen

        # Membership table seeded with the local record (MembershipProtocolImpl.java:131-137).
        self.membership_table: Dict[str, MembershipRecord] = {
            local_member.id: MembershipRecord(local_member, ALIVE, 0)
        }
        self.members: Dict[str, Member] = {local_member.id: local_member}

        self.suspicion_timeout_tasks: Dict[str, Timer] = {}
        self._listeners: List[Callable[[MembershipEvent], None]] = []
        self._trace_listeners: List[Callable] = []
        self._stopped = False
        self._periodic_sync: Optional[Timer] = None

        self._unsubscribe = transport.listen(self._on_message)
        failure_detector.listen(self._on_failure_detector_event)
        gossip_protocol.listen(self._on_gossip_message)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> SimFuture:
        """Initial SYNC to all seeds; resolves when the first acceptable
        SYNC_ACK is merged or the sync timeout elapses
        (MembershipProtocolImpl.java:216-251)."""
        started = SimFuture()
        if not self.seed_members:
            self._schedule_periodic_sync()
            started.resolve(None)
            return started

        def finish(_=None):
            if not started.done:
                self._schedule_periodic_sync()
                started.resolve(None)

        def on_reply(msg: Message):
            if started.done or self._stopped:
                return
            if not self._check_sync_group(msg):
                return
            self._sync_membership(msg.data, on_start=True)
            finish()

        for address in self.seed_members:
            cid = self.cid_generator.next_cid()
            self.transport.request_response(
                self._prepare_sync_msg(SYNC, cid), address, timeout_ms=self.config.sync_timeout
            ).subscribe(on_reply, lambda _err: None)
        # Global timeout: resolve start() even if no seed answered.
        self.sim.schedule(self.config.sync_timeout, finish)
        return started

    def stop(self) -> None:
        self._stopped = True
        if self._periodic_sync is not None:
            self._periodic_sync.cancel()
        for timer in self.suspicion_timeout_tasks.values():
            timer.cancel()
        self.suspicion_timeout_tasks.clear()
        self._unsubscribe()
        self._listeners.clear()
        self._trace_listeners.clear()

    def listen(self, handler: Callable[[MembershipEvent], None]) -> None:
        self._listeners.append(handler)

    def listen_trace(self, handler: Callable) -> None:
        """Subscribe to raw membership-table transitions — the numeric
        event stream shared with the dense tick's trace
        (telemetry/events.py schema; telemetry.events.OracleTraceCollector
        adapts this into ``MembershipTraceEvent`` records).

        ``handler(event_type: TraceEventType, member: Member,
        incarnation: int)`` is called synchronously at the transition,
        BEFORE any metadata fetch — unlike :meth:`listen`'s
        ``MembershipEvent``s, whose ADDED/UPDATED are deferred (and
        possibly suppressed) by the metadata round trip.  The trace is
        the table's transition log; the event stream is the
        application-facing view.
        """
        self._trace_listeners.append(handler)

    # -- views -------------------------------------------------------------

    def member_list(self) -> List[Member]:
        return list(self.members.values())

    def other_members(self) -> List[Member]:
        return [m for m in self.members.values() if m != self.local_member]

    def member_by_id(self, member_id: str) -> Optional[Member]:
        return self.members.get(member_id)

    def member_by_address(self, address: Address) -> Optional[Member]:
        for m in self.members.values():
            if m.address == address:
                return m
        return None

    def membership_records(self) -> List[MembershipRecord]:
        return list(self.membership_table.values())

    @property
    def incarnation(self) -> int:
        return self.membership_table[self.local_member.id].incarnation

    # -- public protocol actions -------------------------------------------

    def update_incarnation(self) -> SimFuture:
        """Bump own incarnation and gossip it — drives metadata version bumps
        (MembershipProtocolImpl.java:176-190, used by ClusterImpl.updateMetadata)."""
        cur = self.membership_table[self.local_member.id]
        new = MembershipRecord(self.local_member, ALIVE, cur.incarnation + 1)
        self.membership_table[self.local_member.id] = new
        return self._spread_membership_gossip(new)

    def leave_cluster(self) -> SimFuture:
        """Self-record -> DEAD at inc+1, gossiped; resolves when the leave
        gossip is swept (MembershipProtocolImpl.java:197-206)."""
        cur = self.membership_table[self.local_member.id]
        new = MembershipRecord(self.local_member, DEAD, cur.incarnation + 1)
        self.membership_table[self.local_member.id] = new
        self._trace(TraceEventType.LEAVING, self.local_member, new.incarnation)
        return self._spread_membership_gossip(new)

    # -- periodic sync (MembershipProtocolImpl.java:298-314,410-421) -------

    def _schedule_periodic_sync(self) -> None:
        self._periodic_sync = self.sim.schedule_periodic(self.config.sync_interval, self._do_sync)

    def _do_sync(self) -> None:
        if self._stopped:
            return
        address = self._select_sync_address()
        if address is None:
            return
        self.transport.send(address, self._prepare_sync_msg(SYNC, None))

    def _select_sync_address(self) -> Optional[Address]:
        addresses = list(
            dict.fromkeys(
                list(self.seed_members) + [m.address for m in self.other_members()]
            )
        )
        if not addresses:
            return None
        return addresses[self.sim.rng.randrange(len(addresses))]

    # -- message handlers --------------------------------------------------

    def _on_message(self, message: Message) -> None:
        if self._stopped or not self._check_sync_group(message):
            return
        if message.qualifier == SYNC:
            self._on_sync(message)
        elif message.qualifier == SYNC_ACK and message.correlation_id is None:
            # Correlated SYNC_ACKs are consumed by the initial-sync
            # request-response path (MembershipProtocolImpl.java:324-330).
            self._sync_membership(message.data, on_start=False)

    def _on_sync(self, message: Message) -> None:
        """Merge then reply SYNC_ACK with our merged table
        (MembershipProtocolImpl.java:346-367)."""
        self._sync_membership(message.data, on_start=False)
        reply = self._prepare_sync_msg(SYNC_ACK, message.correlation_id)
        self.transport.send(message.sender, reply)

    def _on_failure_detector_event(self, event: FailureDetectorEvent) -> None:
        """FD verdicts (MembershipProtocolImpl.java:370-398)."""
        if self._stopped:
            return
        r0 = self.membership_table.get(event.member.id)
        if r0 is None:  # member already removed
            return
        if r0.status == event.status:  # no change
            return
        if event.status == ALIVE:
            # ALIVE won't override SUSPECT — send SYNC to the member instead,
            # forcing it to spread a refutation at inc+1.
            self.transport.send(event.member.address, self._prepare_sync_msg(SYNC, None))
        else:
            record = MembershipRecord(r0.member, event.status, r0.incarnation)
            self._update_membership(record, UpdateReason.FAILURE_DETECTOR_EVENT)

    def _on_gossip_message(self, message: Message) -> None:
        """Membership gossips from the gossip component
        (MembershipProtocolImpl.java:401-408)."""
        if self._stopped:
            return
        if message.qualifier == MEMBERSHIP_GOSSIP:
            self._update_membership(message.data, UpdateReason.MEMBERSHIP_GOSSIP)

    # -- sync plumbing -----------------------------------------------------

    def _check_sync_group(self, message: Message) -> bool:
        """Drop cross-cluster messages (MembershipProtocolImpl.java:431-437)."""
        if isinstance(message.data, SyncData):
            return message.data.sync_group == self.config.sync_group
        return False

    def _prepare_sync_msg(self, qualifier: str, cid: Optional[str]) -> Message:
        records = tuple(self.membership_table.values())
        return Message(
            qualifier=qualifier,
            correlation_id=cid,
            data=SyncData(records, self.config.sync_group),
        )

    def _sync_membership(self, sync_data: SyncData, on_start: bool) -> None:
        """Merge every changed record (MembershipProtocolImpl.java:456-467)."""
        reason = UpdateReason.INITIAL_SYNC if on_start else UpdateReason.SYNC
        for r1 in sync_data.membership:
            if self.membership_table.get(r1.member.id) != r1:
                self._update_membership(r1, reason)

    # -- the merge funnel (MembershipProtocolImpl.java:475-541) ------------

    def _update_membership(self, r1: MembershipRecord, reason: UpdateReason) -> None:
        r0 = self.membership_table.get(r1.member.id)

        if not r1.is_overrides(r0):
            return

        # Self-refutation: record about the local member that overrides ->
        # bump incarnation, keep own status, gossip (:488-509).
        if r1.member.id == self.local_member.id:
            current_incarnation = max(r0.incarnation, r1.incarnation)
            r2 = MembershipRecord(self.local_member, r0.status, current_incarnation + 1)
            self.membership_table[self.local_member.id] = r2
            self._spread_membership_gossip(r2)
            return

        # Update table: accepted DEAD deletes the record (:512-516).
        if r1.status == DEAD:
            self.membership_table.pop(r1.member.id, None)
        else:
            self.membership_table[r1.member.id] = r1

        # Trace stream: the table transition, in the shared numeric
        # schema (telemetry/events.py).  ALIVE-over-ALIVE incarnation
        # bumps are not transitions (the tick emits nothing for them
        # either); the metadata-facing UPDATED surface stays on listen().
        if r1.status == DEAD:
            self._trace(TraceEventType.REMOVED, r1.member, r1.incarnation)
        elif r1.status == SUSPECT and (r0 is None or r0.status != SUSPECT):
            # SUSPECT-over-SUSPECT incarnation bumps are not transitions
            # (the tick's transition trace emits nothing for them either).
            self._trace(TraceEventType.SUSPECTED, r1.member, r1.incarnation)
        elif r1.status == ALIVE and r0 is None:
            self._trace(TraceEventType.ADDED, r1.member, r1.incarnation)
        elif r1.status == ALIVE and r0.status == SUSPECT:
            self._trace(TraceEventType.ALIVE_REFUTED, r1.member,
                        r1.incarnation)

        # Schedule/cancel suspicion timeout (:518-523).
        if r1.status == SUSPECT:
            self._schedule_suspicion_timeout(r1)
        else:
            self._cancel_suspicion_timeout(r1.member.id)

        self._emit_membership_event(r0, r1)

        # Re-gossip unless the update itself arrived by gossip/initial sync (:526-539).
        if reason not in (UpdateReason.MEMBERSHIP_GOSSIP, UpdateReason.INITIAL_SYNC):
            self._spread_membership_gossip(r1)

    # -- events + metadata (MembershipProtocolImpl.java:543-588) -----------

    def _emit_membership_event(self, r0: Optional[MembershipRecord], r1: MembershipRecord) -> None:
        member = r1.member

        if r1.status == DEAD:
            self.members.pop(member.id, None)
            metadata = self.metadata_store.remove_metadata(member)
            self._emit(MembershipEvent.removed(member, metadata))
            return

        if r0 is None and r1.status == ALIVE:
            self.members[member.id] = member
            # ADDED only after the metadata fetch succeeds; a fetch timeout
            # suppresses the event (:558-570 onErrorResume(TimeoutException)).
            self.metadata_store.fetch_metadata(member).subscribe(
                lambda metadata, m=member: self._on_added_metadata(m, metadata),
                lambda _err: None,
            )
            return

        if r0 is not None and r0.incarnation < r1.incarnation:
            self.metadata_store.fetch_metadata(member).subscribe(
                lambda metadata, m=member: self._on_updated_metadata(m, metadata),
                lambda _err: None,
            )

    def _on_added_metadata(self, member: Member, metadata: Dict[str, str]) -> None:
        if self._stopped:
            return
        self.metadata_store.update_metadata_for(member, metadata)
        self._emit(MembershipEvent.added(member, metadata))

    def _on_updated_metadata(self, member: Member, new_metadata: Dict[str, str]) -> None:
        if self._stopped:
            return
        old_metadata = self.metadata_store.update_metadata_for(member, new_metadata)
        self._emit(MembershipEvent.updated(member, old_metadata, new_metadata))

    def _emit(self, event: MembershipEvent) -> None:
        for handler in list(self._listeners):
            handler(event)

    def _trace(self, event_type: TraceEventType, member: Member,
               incarnation: int) -> None:
        for handler in list(self._trace_listeners):
            handler(event_type, member, incarnation)

    # -- suspicion timeouts (MembershipProtocolImpl.java:590-618) ----------

    def _schedule_suspicion_timeout(self, record: MembershipRecord) -> None:
        member_id = record.member.id
        if member_id in self.suspicion_timeout_tasks:
            return  # computeIfAbsent semantics: don't reschedule
        timeout = swim_math.suspicion_timeout(
            self.config.suspicion_mult, len(self.membership_table), self.config.ping_interval
        )
        self.suspicion_timeout_tasks[member_id] = self.sim.schedule(
            timeout, lambda: self._on_suspicion_timeout(member_id)
        )

    def _cancel_suspicion_timeout(self, member_id: str) -> None:
        timer = self.suspicion_timeout_tasks.pop(member_id, None)
        if timer is not None:
            timer.cancel()

    def _on_suspicion_timeout(self, member_id: str) -> None:
        if self._stopped:
            return
        self.suspicion_timeout_tasks.pop(member_id, None)
        record = self.membership_table.get(member_id)
        if record is not None:
            dead = MembershipRecord(record.member, DEAD, record.incarnation)
            self._update_membership(dead, UpdateReason.SUSPICION_TIMEOUT)

    # -- gossip spread (MembershipProtocolImpl.java:620-635) ---------------

    def _spread_membership_gossip(self, record: MembershipRecord) -> SimFuture:
        msg = Message(qualifier=MEMBERSHIP_GOSSIP, data=record)
        return self.gossip_protocol.spread(msg)
