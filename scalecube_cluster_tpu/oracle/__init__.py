"""Event-driven small-N oracle simulator.

The behavioral reference implementation of the framework: faithful per-node
protocol objects (transport, failure detector, gossip, membership, metadata,
cluster facade) driven by a seeded discrete-event loop with virtual time.
It stands in for the reference's in-JVM multi-node test harness
(SURVEY.md §4) and is the cross-check target for the dense TPU tick in
``models/`` (SURVEY.md §7 step 2).
"""

from scalecube_cluster_tpu.oracle.core import (
    Address,
    CorrelationIdGenerator,
    Member,
    SimFuture,
    Simulator,
    TimeoutError_,
)
from scalecube_cluster_tpu.oracle.transport import (
    Message,
    NetworkEmulator,
    NetworkLinkSettings,
    Transport,
)
from scalecube_cluster_tpu.oracle.fdetector import FailureDetector, FailureDetectorEvent
from scalecube_cluster_tpu.oracle.gossip import GossipProtocol
from scalecube_cluster_tpu.oracle.membership import (
    MembershipEvent,
    MembershipProtocol,
    MembershipRecord,
    SyncData,
)
from scalecube_cluster_tpu.oracle.metadata import MetadataStore
from scalecube_cluster_tpu.oracle.cluster import SYSTEM_GOSSIPS, SYSTEM_MESSAGES, Cluster

__all__ = [
    "Address",
    "Cluster",
    "CorrelationIdGenerator",
    "FailureDetector",
    "FailureDetectorEvent",
    "GossipProtocol",
    "Member",
    "MembershipEvent",
    "MembershipProtocol",
    "MembershipRecord",
    "Message",
    "MetadataStore",
    "NetworkEmulator",
    "NetworkLinkSettings",
    "Simulator",
    "SimFuture",
    "SyncData",
    "SYSTEM_GOSSIPS",
    "SYSTEM_MESSAGES",
    "TimeoutError_",
    "Transport",
]
