"""Parameter-sweep harness: vmap one compiled SWIM program over a knob grid.

BASELINE config 5 ("1M-member SWIM parameter sweep: fanout × ping-interval
× suspicion-mult, 10k rounds") and the reference's own experiment design
(GossipProtocolTest.java:50-66 sweeps {N, loss, delay} as a parameterized
matrix).  Here the grid is *data*: models/swim.Knobs carries the sweepable
schedule fields as traced scalars, so a B-point grid is one ``jax.vmap``
over one compiled scan — the TPU-native analog of EP/grid-search
parallelism (SURVEY.md §2.5).

Outputs per grid point, from one crash-at-round-0 scenario:
  - ``dissemination_rounds``: crash → death known by every live observer
    (the SWIM O(log n) dissemination curve's sample),
  - ``detection_rounds``: crash → first DEAD declaration,
  - ``first_false_positive``: first round a live member is suspected,
  - ``false_positive_rate``: FP observer-rounds per observer per round.

``main`` writes the curve artifact (JSON) and checks the analytic
anchors from swim_math (the ClusterMath port): measured dissemination must
sit within the spread window `repeat_mult*ceil(log2(n+1))` and detection
must straddle the configured suspicion timeout.

Performance note: shift-mode sweeps default to SHARED-SHIFT BATCHING —
the per-round channel shifts come from one unbatched key, so under vmap
the payload dynamic-slices stay batch-invariant slices and the whole
grid runs at the contiguous-slice rate at any N (one compiled program
sweeps a 27-cell grid at 1M members; experiments/sweep_1m.py).  With
per-instance shifts (share_shifts=False) the slices lower to gathers and
degrade ~3 orders of magnitude above ~16k members.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import warnings
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from scalecube_cluster_tpu import swim_math
from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.models import swim


def knob_grid(params: swim.SwimParams, *,
              fanout: Sequence[int] = (),
              ping_every: Sequence[int] = (),
              suspicion_rounds: Sequence[int] = (),
              loss_probability: Sequence[float] = (),
              sync_every: Sequence[int] = ()) -> swim.Knobs:
    """Cartesian grid of knob values as one batched Knobs pytree [B].

    Unspecified axes stay at the params value.  ``fanout`` entries must be
    <= params.fanout (the static channel count).
    """
    axes = {
        "fanout": list(fanout) or [params.fanout],
        "ping_every": list(ping_every) or [params.ping_every],
        "suspicion_rounds": list(suspicion_rounds) or [params.suspicion_rounds],
        "loss_probability": list(loss_probability) or [params.loss_probability],
        "sync_every": list(sync_every) or [params.sync_every],
    }
    if max(axes["fanout"]) > params.fanout:
        raise ValueError(
            f"fanout sweep max {max(axes['fanout'])} exceeds the static "
            f"channel count params.fanout={params.fanout}"
        )
    points = list(itertools.product(*axes.values()))
    cols = list(zip(*points))
    named = dict(zip(axes.keys(), cols))
    return swim.Knobs(
        fanout=jnp.asarray(named["fanout"], jnp.int32),
        ping_every=jnp.asarray(named["ping_every"], jnp.int32),
        suspicion_rounds=jnp.asarray(named["suspicion_rounds"], jnp.int32),
        loss_probability=jnp.asarray(named["loss_probability"], jnp.float32),
        sync_every=jnp.asarray(named["sync_every"], jnp.int32),
    )


def sweep_run(base_key, params: swim.SwimParams, world: swim.SwimWorld,
              n_rounds: int, knobs: swim.Knobs,
              share_shifts: Optional[bool] = None):
    """Run the scenario once per grid point: vmap over the knob batch.

    Returns metrics with a leading grid axis [B, n_rounds, ...].  Each grid
    point gets an independent PRNG stream (fold_in of its index).

    ``share_shifts`` (default: on for shift delivery): source the
    per-round channel shifts from ONE unbatched key shared by every grid
    point, so the payload dynamic-slices stay batch-invariant slices
    under vmap instead of lowering to gathers — this is what makes the
    1M-member 27-cell grid ONE compiled program at the contiguous-slice
    rate (measured in experiments/sweep_1m.py; without it the vmapped
    shift sweep degraded ~3 orders of magnitude above ~16k members).
    Within each instance the draw distribution is unchanged; across
    instances the shared offsets act as common random numbers for the
    channel topology, while loss/chain/verdict draws remain independent
    per instance (swim.swim_tick docstring).
    """
    if share_shifts is None:
        share_shifts = params.delivery == "shift"
    batch = knobs.fanout.shape[0]
    keys = jax.vmap(lambda i: jax.random.fold_in(base_key, i))(
        jnp.arange(batch, dtype=jnp.int32)
    )
    shift_key = base_key if share_shifts else None

    def one(key, kn):
        _, metrics = swim.run(key, params, world, n_rounds, knobs=kn,
                              shift_key=shift_key)
        return metrics

    return jax.vmap(one)(keys, knobs)


def crash_curves(metrics: Dict[str, np.ndarray], subject_slot: int,
                 n_rounds: int, n_members: int) -> Dict[str, np.ndarray]:
    """Digest sweep metrics into the headline curves, one value per grid
    point (see module docstring)."""
    suspects = np.asarray(metrics["suspect"])[:, :, subject_slot]    # [B, T]
    deads = np.asarray(metrics["dead"])[:, :, subject_slot]
    alive_view = np.asarray(metrics["alive"])[:, :, subject_slot]
    fp = np.asarray(metrics["false_positives"]).sum(axis=2)          # [B, T]

    def first(cond):  # [B, T] -> [B] (n_rounds = never)
        hit = cond.any(axis=1)
        idx = cond.argmax(axis=1)
        return np.where(hit, idx, n_rounds).astype(np.float64)

    return {
        "detection_rounds": first(deads > 0),
        "dissemination_rounds": first(
            (alive_view == 0) & (suspects == 0) & (deads > 0)
        ),
        "first_false_positive": first(fp > 0),
        "false_positive_rate": fp.mean(axis=1) / n_members,
    }


# Above this N, a vmapped shift-mode sweep with PER-INSTANCE shifts
# (share_shifts=False) degrades to gathers and silently runs orders of
# magnitude below the un-vmapped shift path.  The default shared-shift
# batching (sweep_run docstring) removes the degradation — this constant
# and the warning below only guard the explicit opt-out.
SHIFT_VMAP_N_WARN = 16_384


def run_crash_sweep(n_members: int, n_rounds: int, config=None, seed: int = 0,
                    delivery: str = "shift",
                    n_subjects: Optional[int] = None,
                    share_shifts: Optional[bool] = None,
                    **grid_axes) -> Dict[str, object]:
    """One-call sweep: crash-at-0 scenario across the knob grid.

    Shift delivery defaults to shared-shift batching (sweep_run
    docstring), which keeps the vmapped grid at the contiguous-slice
    rate at any N; opting out (``share_shifts=False``) above
    ``SHIFT_VMAP_N_WARN`` members warns, because per-instance shifts
    lower to gathers under vmap.
    """
    if (delivery == "shift" and share_shifts is False
            and n_members > SHIFT_VMAP_N_WARN):
        warnings.warn(
            f"vmapped shift-mode sweep with share_shifts=False at "
            f"n_members={n_members} > {SHIFT_VMAP_N_WARN}: per-instance "
            f"dynamic-slices lower to gathers under vmap and run at the "
            f"slow random-access rate.  Use the default shared-shift "
            f"batching or delivery='scatter'.",
            stacklevel=2,
        )
    config = config or ClusterConfig.default()
    params = swim.SwimParams.from_config(
        config, n_members=n_members, n_subjects=n_subjects,
        delivery=delivery,
        # Static channel count must cover the largest swept fanout.
        **({"fanout": max(grid_axes["fanout"])} if grid_axes.get("fanout")
           else {}),
    )
    world = swim.SwimWorld.healthy(params).with_crash(0, at_round=0)
    knobs = knob_grid(params, **grid_axes)
    metrics = sweep_run(jax.random.key(seed), params, world, n_rounds, knobs,
                        share_shifts=share_shifts)
    curves = crash_curves(metrics, subject_slot=0, n_rounds=n_rounds,
                          n_members=n_members)
    grid_cols = {
        f.name: np.asarray(getattr(knobs, f.name)).tolist()
        for f in dataclasses.fields(knobs)
    }
    return {
        "n_members": n_members,
        "n_rounds": n_rounds,
        "delivery": delivery,
        "grid": grid_cols,
        "curves": {k: v.tolist() for k, v in curves.items()},
        "analytic": {
            "periods_to_spread": swim_math.gossip_periods_to_spread(
                config.gossip_repeat_mult, n_members
            ),
            "suspicion_rounds_default": params.suspicion_rounds,
        },
    }


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n-members", type=int, default=4096)
    ap.add_argument("--n-subjects", type=int, default=None)
    ap.add_argument("--n-rounds", type=int, default=600)
    ap.add_argument("--delivery", default="shift")
    ap.add_argument("--fanout", type=int, nargs="*", default=[2, 3, 4])
    ap.add_argument("--ping-every", type=int, nargs="*", default=[2, 5])
    ap.add_argument("--suspicion-rounds", type=int, nargs="*", default=[])
    ap.add_argument("--loss", type=float, nargs="*", default=[0.0, 0.05])
    ap.add_argument("--out", default="sweep_curves.json")
    args = ap.parse_args(argv)

    result = run_crash_sweep(
        args.n_members, args.n_rounds,
        n_subjects=args.n_subjects,
        delivery=args.delivery,
        fanout=args.fanout,
        ping_every=args.ping_every,
        suspicion_rounds=args.suspicion_rounds,
        loss_probability=args.loss,
    )
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    det = result["curves"]["detection_rounds"]
    dis = result["curves"]["dissemination_rounds"]
    print(f"wrote {args.out}: {len(det)} grid points; "
          f"detection rounds {min(det)}..{max(det)}, "
          f"dissemination {min(dis)}..{max(dis)}")


if __name__ == "__main__":
    main()
