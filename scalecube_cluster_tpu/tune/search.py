"""Device-parallel protocol autotuning: sweep the knob grid in ONE
compiled program per shape bucket, score on the PR-5 SLOs, keep the
Pareto frontier.

The sweep is the payoff of the dynamic-:class:`~.models.swim.Knobs`
split: ``SwimParams`` stays a static jit argument (shapes, channel
counts), the swept schedule fields are traced DATA.  A knob-grid ×
scenario-batch product therefore runs as

  - one :func:`~.models.compose.composed_batch_scan` call per
    (config, shape-bucket) pair — scenarios vmapped on the batch axis,
    the scan outside the vmap (the PR-12 batching layout);
  - ZERO recompiles across the whole grid: every config reruns the
    bucket's already-compiled program with different knob operands.
    :func:`sweep` returns the jit cache size as the witness
    (``info["compiles"] == info["shape_buckets"]``, pinned by
    tests/test_tune.py and recorded in artifacts/tune_pareto.json).

Scoring rides the composed plane stack — event trace ⊕ SAFETY-ONLY
monitor (``MonitorSpec.passive``) — so every config is scored on:

  ==============================  =======================================
  objective (minimize)            source
  ==============================  =======================================
  false_positive_observer_rate    trace ``first_suspect`` strictly before
                                  the subject's scheduled crash round
                                  (never-faulty subjects included), over
                                  eligible (live observer, live subject)
                                  pairs
  detection_latency_p99_rounds    ``first_suspect`` - ``down_from`` P99
                                  over (live observer, permanently
                                  crashed subject) pairs, censored at the
                                  horizon
  removal_latency_p99_rounds      same, ``first_removed``
  wire_bytes_per_member_round     measured ``messages_*`` counters priced
                                  by the parallel/traffic.py wire format
                                  (gossip/SYNC payloads, probe headers)
  ==============================  =======================================

The monitor runs the *passive* spec on purpose: scenario-derived
completeness deadlines are built for the DEFAULT schedule, and a
slower-but-valid config (low-traffic) would trip them spuriously —
knob data cannot rebuild host-side deadlines.  Safety invariants
(monotone incarnations, timer bounds, wire saturation) gate every
config; liveness is what the objectives measure.  Shipped profiles
additionally rerun the FULL fuzz oracle as static params
(:func:`validate_profile`), where the deadlines DO adapt.
"""

from __future__ import annotations

import itertools
from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from scalecube_cluster_tpu.chaos import campaign as ccampaign
from scalecube_cluster_tpu.chaos import monitor as cmonitor
from scalecube_cluster_tpu.chaos import scenarios as cscenarios
from scalecube_cluster_tpu.models import compose, swim
from scalecube_cluster_tpu.parallel import traffic
from scalecube_cluster_tpu.telemetry import trace as ttrace
from scalecube_cluster_tpu.tune import profiles as tprofiles

INT32_MAX = np.iinfo(np.int32).max

OBJECTIVES = (
    "false_positive_observer_rate",
    "detection_latency_p99_rounds",
    "removal_latency_p99_rounds",
    "wire_bytes_per_member_round",
)

# Default tune-workload params overrides: the health planes the grid
# sweeps must be ON in the static params (their knobs clamp AGAINST
# these ceilings — Knobs.for_params) and the campaign preset ships
# them disabled.
TUNE_PARAM_OVERRIDES = {"lhm_max": 8, "dead_suppress_rounds": 16}

# Event-lane capacity for the scoring trace plane: the SLOs read the
# ``first_suspect``/``first_removed`` matrices, which update regardless
# of lane occupancy — a small lane keeps the batched carry cheap.
DEFAULT_TRACE_CAPACITY = 64


# --------------------------------------------------------------------------
# Grid construction
# --------------------------------------------------------------------------


def default_grid(params: "swim.SwimParams",
                 smoke: bool = False) -> List[dict]:
    """The default knob grid for ``params``: config dicts
    ``{"name", "overrides"}``, reference default FIRST (empty
    overrides — the row every shipped profile must stay
    Pareto-non-dominated against).

    The probe axes (cadence × timeout × suspicion window) form a full
    product — they interact directly in the FD chain; the suppression
    and health caps (``dead_suppress_rounds``, ``lhm_max``,
    ``sync_every``) get one-off arms off the reference — second-order
    interactions, and each arm is free anyway (the compiled program is
    shared).  Axes for planes the params disable are skipped; smoke
    keeps only the cadence × timeout core.  Every override is
    validated by ``Knobs.for_params`` at sweep time."""
    half_to = max(1.0, float(params.ping_timeout_ms) / 2)
    axes = {
        "ping_every": sorted({1, int(params.ping_every)}),
        "ping_timeout_ms": [half_to, float(params.ping_timeout_ms)],
    }
    if not smoke:
        axes["suspicion_rounds"] = sorted({
            max(1, params.suspicion_rounds // 2),
            params.suspicion_rounds,
            2 * params.suspicion_rounds,
        })
    names = sorted(axes)
    configs = [{"name": "reference", "overrides": {}}]
    seen = {()}

    def add(ov: dict) -> None:
        key = tuple(sorted(ov.items()))
        if key in seen:
            return
        seen.add(key)
        label = ",".join(f"{n}={ov[n]:g}" if isinstance(ov[n], float)
                         else f"{n}={ov[n]}" for n in sorted(ov))
        configs.append({"name": label, "overrides": ov})

    for combo in itertools.product(*(axes[n] for n in names)):
        add({n: v for n, v in zip(names, combo)
             if _differs(v, getattr(params, n))})
    if not smoke:
        if params.lhm_max > 1:
            add({"lhm_max": 1})
        if params.dead_suppress_rounds > 1:
            add({"dead_suppress_rounds":
                 max(1, params.dead_suppress_rounds // 2)})
        if params.sync_every > 0:
            add({"sync_every": 2 * params.sync_every})
    return configs


def _differs(value, base) -> bool:
    return float(value) != float(base)


def profile_configs(params: "swim.SwimParams") -> List[dict]:
    """The shipped profiles as sweep configs (same row schema as
    :func:`default_grid`), overrides resolved against ``params``."""
    return [{"name": name,
             "overrides": tprofiles.resolve(name, params),
             "profile": True}
            for name in sorted(tprofiles.PROFILES)]


# --------------------------------------------------------------------------
# The compiled sweep arms
# --------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("params", "n_rounds", "capacity",
                                   "trace_capacity"))
def _sweep_bucket(base_keys, params, worlds, specs, n_rounds, knobs,
                  capacity, trace_capacity):
    """One (config, bucket) arm: the scored plane stack over the
    batched composed scan.  Knobs are traced operands — every config
    reruns this program; ``_sweep_bucket._cache_size()`` is the
    one-compile-per-shape-bucket witness."""
    planes = (ttrace.TracePlane(capacity=trace_capacity),
              cmonitor.MonitorPlane(specs, capacity=capacity))
    _, results, metrics = compose.composed_batch_scan(
        base_keys, params, worlds, n_rounds, planes=planes, knobs=knobs)
    return results["trace"], results["monitor"], metrics


@partial(jax.jit, static_argnames=("params", "n_rounds", "capacity",
                                   "trace_capacity"))
def _row_run(key, params, world, spec, n_rounds, knobs, capacity,
             trace_capacity):
    """The sequential control arm (bench.py --tune speedup ratio): the
    SAME plane stack through the single-scenario composed scan."""
    planes = (ttrace.TracePlane(capacity=trace_capacity),
              cmonitor.MonitorPlane(spec, capacity=capacity))
    _, results, metrics = compose.composed_scan(
        key, params, world, n_rounds, planes=planes, knobs=knobs)
    return results["trace"], results["monitor"], metrics


def passive_specs(params: "swim.SwimParams", batch: int):
    """``MonitorSpec.passive`` stacked to the bucket batch size."""
    spec = cmonitor.MonitorSpec.passive(params)
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (batch,) + x.shape), spec)


def config_knobs(params: "swim.SwimParams", overrides: dict,
                 batch: int) -> "swim.Knobs":
    """One config's overrides as VALIDATED batched knob data (the same
    knob row broadcast to every scenario in the bucket)."""
    kn = swim.Knobs.for_params(params, **overrides)
    kn = jax.tree.map(jnp.asarray, kn)
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (batch,) + x.shape), kn)


# --------------------------------------------------------------------------
# Scoring
# --------------------------------------------------------------------------


def wire_bytes_total(params: "swim.SwimParams", metrics: dict) -> float:
    """Measured message counters priced by the wire format
    (parallel/traffic.py byte model): gossip and anti-entropy messages
    carry full K-record payloads, probe-plane messages one packed
    record header.  ``_sent`` counters only — received/verdict
    counters would double-count the same wire bytes."""
    kb = traffic._key_bytes(params)
    payload = params.n_subjects * kb
    per_message = {
        "messages_gossip": payload,
        "messages_ping_sent": kb,
        "messages_ping_req_sent": kb,
        "messages_anti_entropy": 2 * params.n_subjects * kb,
    }
    total = 0.0
    for name, cost in per_message.items():
        if name in metrics:
            total += float(np.asarray(metrics[name]).sum()) * cost
    return total


def _score_bucket(bucket, tel, metrics) -> dict:
    """Host-side partial SLO aggregates for one (config, bucket) arm."""
    horizon = bucket.horizon
    down = np.asarray(bucket.worlds.down_from)          # [B, N]
    down_until = np.asarray(bucket.worlds.down_until)   # [B, N]
    leave = np.asarray(bucket.worlds.leave_at)          # [B, N]
    sids = np.asarray(bucket.worlds.subject_ids)        # [B, K]
    fs = np.asarray(tel.first_suspect)                  # [B, N, K]
    fr = np.asarray(tel.first_removed)                  # [B, N, K]
    rows = np.arange(fs.shape[0])[:, None]
    subj_down = down[rows, sids]                        # [B, K]
    subj_down_until = down_until[rows, sids]
    subj_leave = leave[rows, sids]

    # Eligible pairs: observers that never crash or leave, subjects
    # that never leave (graceful LEAVE makes any suspicion moot).
    obs_ok = (down == INT32_MAX) & (leave == INT32_MAX)     # [B, N]
    subj_ok = subj_leave == INT32_MAX                       # [B, K]
    pair_ok = obs_ok[:, :, None] & subj_ok[:, None, :]      # [B, N, K]

    # False positive: first suspicion strictly before the subject's
    # crash round (INT32_MAX when it never crashes).
    false = pair_ok & (fs < subj_down[:, None, :])

    # Latency pools: permanently crashed subjects only (revivals make
    # "detected" ambiguous), suspicion at-or-after the crash (earlier
    # ones are already counted as false positives), censored at the
    # horizon when the observer never converged.
    dead = subj_ok & (subj_down < horizon) & (subj_down_until == INT32_MAX)
    det_pair = obs_ok[:, :, None] & dead[:, None, :] & (
        fs >= subj_down[:, None, :])
    rem_pair = obs_ok[:, :, None] & dead[:, None, :] & (
        fr >= subj_down[:, None, :])
    lat_det = np.minimum(fs, horizon) - subj_down[:, None, :]
    lat_rem = np.minimum(fr, horizon) - subj_down[:, None, :]

    return {
        "fp_pairs": int(false.sum()),
        "eligible_pairs": int(pair_ok.sum()),
        "detection_rounds": lat_det[det_pair],
        "removal_rounds": lat_rem[rem_pair],
        "wire_bytes": wire_bytes_total(bucket.params, metrics),
        "member_rounds": bucket.size * bucket.params.n_members * horizon,
    }


def _finalize_slos(parts: List[dict]) -> dict:
    det = np.concatenate([p["detection_rounds"] for p in parts]) \
        if parts else np.zeros((0,))
    rem = np.concatenate([p["removal_rounds"] for p in parts]) \
        if parts else np.zeros((0,))
    eligible = sum(p["eligible_pairs"] for p in parts)
    member_rounds = sum(p["member_rounds"] for p in parts)
    return {
        "false_positive_observer_rate":
            (sum(p["fp_pairs"] for p in parts) / eligible)
            if eligible else 0.0,
        "detection_latency_p99_rounds":
            float(np.percentile(det, 99)) if det.size else 0.0,
        "removal_latency_p99_rounds":
            float(np.percentile(rem, 99)) if rem.size else 0.0,
        "wire_bytes_per_member_round":
            (sum(p["wire_bytes"] for p in parts) / member_rounds)
            if member_rounds else 0.0,
        "latency_samples": int(det.size),
    }


# --------------------------------------------------------------------------
# The sweep
# --------------------------------------------------------------------------


def tune_scenarios(seed: int, n_scenarios: int, n: int = 32,
                   log=None) -> list:
    """The tune workload: generated campaign scenarios WITHOUT
    open-world joins (join storms flip ``open_world`` params and the
    latency accounting has no fault round for joiners).  Dropped
    scenarios are logged, never silent."""
    scens = cscenarios.generate_campaign(seed, n_scenarios, n=n)
    kept = [s for s in scens if not s.has_joins]
    if log is not None and len(kept) < len(scens):
        log(f"tune: dropped {len(scens) - len(kept)}/{len(scens)} "
            f"join-storm scenarios (open-world rows are out of the "
            f"latency accounting)")
    return kept


def sweep(scenarios: Sequence, configs: Optional[List[dict]] = None,
          seed: int = 0, delivery: str = "shift", capacity: int = 256,
          trace_capacity: int = DEFAULT_TRACE_CAPACITY,
          smoke: bool = False, log=None, **param_overrides):
    """Run every config over every scenario bucket; returns
    ``(rows, info)``.

    ``rows[i]`` = ``{"name", "overrides", "green", "slos"}`` for
    ``configs[i]`` (default: :func:`default_grid` + the shipped
    profiles); ``green`` is the passive safety monitor's verdict over
    ALL scenarios.  ``info`` carries the compile witness: with B
    shape buckets and C configs, ``calls == B * C`` but
    ``compiles == B`` — knob data never recompiles.
    ``param_overrides`` (default :data:`TUNE_PARAM_OVERRIDES`) shape
    the STATIC tune-workload params, identical for every config."""
    overrides = dict(TUNE_PARAM_OVERRIDES)
    overrides.update(param_overrides)
    buckets = ccampaign.build_buckets(scenarios, seed=seed,
                                      delivery=delivery, **overrides)
    if configs is None:
        configs = default_grid(buckets[0].params, smoke=smoke)
        configs += profile_configs(buckets[0].params)
    cache_before = _sweep_bucket._cache_size()
    specs = [passive_specs(b.params, b.size) for b in buckets]
    rows = []
    for cfg in configs:
        parts, green = [], True
        for b, spec in zip(buckets, specs):
            kn = config_knobs(b.params, cfg["overrides"], b.size)
            tel, mon, metrics = _sweep_bucket(
                b.keys, b.params, b.worlds, spec, b.horizon, kn,
                capacity, trace_capacity)
            green &= all(cmonitor.verdict(m)["green"]
                         for m in cmonitor.unstack_monitor(mon))
            parts.append(_score_bucket(b, tel, metrics))
        rows.append({"name": cfg["name"],
                     "overrides": dict(cfg["overrides"]),
                     "profile": bool(cfg.get("profile")),
                     "green": bool(green),
                     "slos": _finalize_slos(parts)})
        if log is not None:
            s = rows[-1]["slos"]
            log(f"tune config {cfg['name']}: green={green} "
                + " ".join(f"{k}={s[k]:.4g}" for k in OBJECTIVES))
    info = {
        "shape_buckets": len(buckets),
        "bucket_sizes": [b.size for b in buckets],
        "configs": len(configs),
        "calls": len(buckets) * len(configs),
        "compiles": _sweep_bucket._cache_size() - cache_before,
        "scenarios": sum(b.size for b in buckets),
        "member_rounds": sum(b.size * b.params.n_members * b.horizon
                             for b in buckets),
        "param_overrides": overrides,
    }
    return rows, info


# --------------------------------------------------------------------------
# Pareto frontier
# --------------------------------------------------------------------------


def dominates(a: Dict[str, float], b: Dict[str, float],
              objectives: Sequence[str] = OBJECTIVES) -> bool:
    """True when ``a`` is at-least-as-good on every objective and
    strictly better on one (minimization)."""
    return (all(a[o] <= b[o] for o in objectives)
            and any(a[o] < b[o] for o in objectives))


def pareto_front(slos: Sequence[Dict[str, float]],
                 objectives: Sequence[str] = OBJECTIVES) -> List[int]:
    """Indices of the non-dominated rows (stable order; duplicates of
    a frontier point all stay on the frontier)."""
    return [i for i, a in enumerate(slos)
            if not any(dominates(b, a, objectives)
                       for j, b in enumerate(slos) if j != i)]


# --------------------------------------------------------------------------
# Profile validation: the held-out fuzz oracle
# --------------------------------------------------------------------------


def validate_profile(profile: str, seed: int = 7001,
                     seeds_per_tier: int = 1, n: int = 16,
                     capacity: int = 256, delivery: str = "shift",
                     log=None) -> dict:
    """Rerun the chaos fuzz oracle with ``profile`` baked into the
    STATIC params on held-out seeds: the full per-scenario
    ``MonitorSpec`` (completeness deadlines and all) is rebuilt under
    the profile's schedule, so a profile that breaks liveness — not
    just safety — goes red.  Returns the campaign summary dict plus
    ``green``."""
    scens = cscenarios.generate_fuzz_campaign(seed, seeds_per_tier, n=n)
    base = ccampaign.campaign_params(scens[0], delivery=delivery)
    overrides = tprofiles.resolve(profile, base)
    buckets = ccampaign.build_buckets(scens, seed=seed,
                                      delivery=delivery, **overrides)
    res = ccampaign.run_campaign_vmapped(
        scens, seed=seed, delivery=delivery, capacity=capacity,
        buckets=buckets)
    summary = res.summary()
    if log is not None:
        log(f"tune profile {profile}: fuzz oracle "
            f"{summary['green_scenarios']}/{summary['scenarios']} green "
            f"on held-out seed {seed} (overrides {overrides})")
    return {"profile": profile, "seed": seed, "overrides": overrides,
            "green": bool(summary["green"]),
            "green_scenarios": summary["green_scenarios"],
            "scenarios": summary["scenarios"],
            "violations_by_code": summary["violations_by_code"]}
