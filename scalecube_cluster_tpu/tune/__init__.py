"""Protocol autotuning: one-compile knob-grid sweeps
(:mod:`~scalecube_cluster_tpu.tune.search`) and the shipped
tuned-default profiles (:mod:`~scalecube_cluster_tpu.tune.profiles`,
surfaced as ``swim.SwimParams.tuned``)."""

from scalecube_cluster_tpu.tune.profiles import (  # noqa: F401
    PROFILES, profile_knobs, tuned_params,
)
from scalecube_cluster_tpu.tune.search import (  # noqa: F401
    OBJECTIVES, default_grid, dominates, pareto_front, sweep,
    tune_scenarios, validate_profile,
)
