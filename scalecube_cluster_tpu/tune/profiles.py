"""Named tuned-default profiles — the autotuner's shipped picks.

Each profile is a small set of *schedule* overrides resolved against a
base :class:`~scalecube_cluster_tpu.models.swim.SwimParams` (all of
them fields that :class:`~scalecube_cluster_tpu.models.swim.Knobs` can
also sweep dynamically, so the sweep that selected them and the params
that ship them describe the same program).  Three ways to consume one:

  - ``swim.SwimParams.tuned("fast-detect")`` — static params with the
    profile baked in (new deployments);
  - :func:`profile_knobs` — the same overrides as validated dynamic
    :class:`Knobs` data for an EXISTING compiled program (same shapes,
    zero recompiles — retuning a running cluster);
  - :func:`tune.search.sweep` rows named after the profile — how the
    bench measures them against the reference default.

Every shipped profile is regress-gated (telemetry/query.py): it must
stay Pareto-non-dominated by the reference default on the sweep
objectives and pass the held-out chaos fuzz oracle
(:func:`tune.search.validate_profile`) with zero violations.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from scalecube_cluster_tpu.models import swim

# name -> {target objective, rationale, resolve(params) -> overrides}.
# ``resolve`` returns CONCRETE values for a given base so the same
# profile scales with the base schedule instead of hardcoding one
# cluster's round quantization.
PROFILES: Dict[str, dict] = {
    "fast-detect": {
        "target": "detection_latency_p99_rounds",
        "why": ("probe every round, half the probe timeout and half the "
                "suspicion window: crashes mature into DEAD verdicts in "
                "roughly half the rounds, trading a higher (still "
                "monitor-green) false-suspicion rate"),
        "resolve": lambda p: {
            "ping_every": 1,
            "ping_timeout_ms": max(1.0, float(p.ping_timeout_ms) / 2),
            "suspicion_rounds": max(1, p.suspicion_rounds // 2),
        },
    },
    "low-traffic": {
        "target": "wire_bytes_per_member_round",
        "why": ("half the probe cadence and half the anti-entropy "
                "cadence: the dominant per-round wire costs (PING "
                "fan-out and SYNC table exchanges) are issued half as "
                "often while gossip dissemination is untouched"),
        "resolve": lambda p: {
            "ping_every": 2 * p.ping_every,
            **({"sync_every": 2 * p.sync_every} if p.sync_every else {}),
        },
    },
    "churn-hardened": {
        "target": "false_positive_observer_rate",
        "why": ("half the probe cadence, probe timeout stretched to "
                "90% of the interval and a 1.5x suspicion window: each "
                "flaky link gets half as many chances per horizon to "
                "produce a false suspicion, slow (not lost) replies "
                "stop counting as timeouts, and the suspicions that do "
                "fire have time to be refuted before maturing into "
                "false removals — at the cost of slower true-crash "
                "detection (unlike low-traffic, anti-entropy keeps its "
                "default cadence, so partitions still heal on time)"),
        "resolve": lambda p: {
            "ping_every": 2 * p.ping_every,
            "ping_timeout_ms": 0.9 * float(p.ping_interval_ms),
            "suspicion_rounds":
                p.suspicion_rounds + (p.suspicion_rounds + 1) // 2,
        },
    },
}


def resolve(profile: str, params: "swim.SwimParams") -> dict:
    """The profile's concrete override dict for ``params``."""
    if profile not in PROFILES:
        raise ValueError(f"unknown tuned profile {profile!r} "
                         f"(have {sorted(PROFILES)})")
    return dict(PROFILES[profile]["resolve"](params))


def profile_knobs(profile: str, params: "swim.SwimParams") -> "swim.Knobs":
    """The profile as validated dynamic knob data for ``params`` —
    reruns an already-compiled program (knobs are traced operands).
    Only overrides that stay within the params ceilings can ship this
    way (``Knobs.for_params`` raises otherwise)."""
    return swim.Knobs.for_params(params, **resolve(profile, params))


def tuned_params(profile: str, base: Optional["swim.SwimParams"] = None,
                 n_members: int = 32, **overrides) -> "swim.SwimParams":
    """Static params with ``profile`` baked in (the
    ``SwimParams.tuned`` constructor body).  ``base`` defaults to the
    chaos-campaign timing preset at ``n_members``; explicit
    ``**overrides`` win over the profile's."""
    if base is None:
        from scalecube_cluster_tpu.chaos import campaign
        base = swim.SwimParams.from_config(
            campaign.campaign_config(), n_members=n_members,
            delivery="shift")
    vals = resolve(profile, base)
    vals.update(overrides)
    return dataclasses.replace(base, **vals)
