"""Counter-based randomness for the dense protocol tick.

The reference draws randomness imperatively per node (`ThreadLocalRandom` +
`Collections.shuffle`, e.g. fdetector/FailureDetectorImpl.java:338-361,
gossip/GossipProtocolImpl.java:252-273) — unseeded, so failures don't
reproduce (SURVEY.md §4 weaknesses).  The TPU tick inverts this: every draw
is a pure function of ``(experiment key, round index)`` via ``fold_in``, so
runs are bit-reproducible and — crucially for sharding — every device can
regenerate any other device's draws without communication (SURVEY.md §7
"sharded randomized peer selection without gathers").

All helpers take an already-folded per-round key; callers derive it with
:func:`round_key` once per tick.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def round_key(base_key, round_idx):
    """Per-round PRNG key: fold the round counter into the experiment key."""
    return jax.random.fold_in(base_key, round_idx)


def targets_excluding_self(key, n_senders: int, n_members: int, fanout: int,
                           sender_offset: int = 0):
    """Uniform random message targets, self excluded: ``[n_senders, fanout]``.

    Models the reference's fanout-member selection
    (gossip/GossipProtocolImpl.java:252-273: a fanout-sized window over a
    shuffled remote-member list).  Deviation, documented: the reference picks
    *distinct* members per round; we draw with replacement, which at fanout F
    collides with probability ~F²/n — negligible for the statistical regimes
    this simulator targets and tolerated by the protocol (delivery dedups,
    GossipProtocolImpl.java:176-180).

    ``sender_offset`` is the global row index of sender 0 (for sharded
    callers whose local rows are a slice of the global member axis).

    Precondition: ``n_members >= 2`` (with one member there is no valid
    non-self target and the randint range below would be empty).
    """
    assert n_members >= 2, "targets_excluding_self requires n_members >= 2"
    # maxval = n_members - 1 is intentional: draws land in [0, n-2] and the
    # shift-past-self below maps them onto the n-1 non-self members.
    draws = jax.random.randint(key, (n_senders, fanout), 0, n_members - 1)
    sender_ids = jnp.arange(n_senders, dtype=draws.dtype)[:, None] + sender_offset
    # Shift draws >= self up by one: uniform over the other n-1 members.
    return jnp.where(draws >= sender_ids, draws + 1, draws)


def bernoulli_mask(key, prob, shape):
    """Per-message loss draw (NetworkLinkSettings.evaluateLoss analog).

    Reference: transport/NetworkLinkSettings.java:54-57 (``p% Bernoulli``).
    ``prob`` may be a scalar or broadcastable per-sender/per-edge array.
    """
    return jax.random.uniform(key, shape) < prob


def exponential_delay(key, mean_ms, shape):
    """Exponential per-hop delay draw (NetworkLinkSettings.evaluateDelay).

    Reference: transport/NetworkLinkSettings.java:64-74 —
    ``-ln(1-U) * mean`` with U uniform in [0, 1).
    """
    u = jax.random.uniform(key, shape)
    return -jnp.log1p(-u) * mean_ms


def choose_eligible(key, eligible, axis: int = -1):
    """Uniformly choose one index among ``eligible`` entries per row.

    Vectorized analog of the reference's "pick a random live member"
    (fdetector/FailureDetectorImpl.java:338-347 selects from the current
    peer list).  Uses the Gumbel-argmax trick so it stays one fused
    elementwise pass + reduce on the VPU.

    Returns ``(index, any_eligible)``; ``index`` is arbitrary (0) where no
    entry is eligible — callers must gate on ``any_eligible``.
    """
    gumbel = jax.random.gumbel(key, eligible.shape)
    scores = jnp.where(eligible, gumbel, -jnp.inf)
    idx = jnp.argmax(scores, axis=axis)
    any_eligible = jnp.any(eligible, axis=axis)
    return idx, any_eligible
