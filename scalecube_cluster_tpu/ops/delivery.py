"""Dense message delivery: the TPU-native transport fast path.

This is the ``TpuSimTransport`` seam from SURVEY.md §5.8: instead of netty
sockets (reference: transport/TransportImpl.java:257-269, ``send0`` piping
each message through the NetworkEmulator and a TCP connection), a round's
worth of messages is one batched tensor exchange:

  - a *record* (subject status + incarnation) packs into one int32 sort key
    whose max implements the SWIM merge winner (records.merge_key);
  - "send" = scatter the sender's packed row into the receivers' inbox
    with a max combiner; duplicate targets fold associatively, so the
    scatter IS the merge — no per-message materialization;
  - "listen" = read your inbox row next round.

Timeouts become round comparisons, correlation ids become (round, slot)
indices, and the NetworkEmulator's per-link loss/delay becomes the ``drop``
mask argument (SURVEY.md §7 design mapping).

Sharding: receivers (rows) are sharded over devices; each device scatters
its local senders' messages into a full-width inbox contribution and the
cross-device combine is a single ``pmax`` (see parallel/mesh.py) — the
ICI-collective analog of the reference's point-to-point TCP.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from scalecube_cluster_tpu import records

# Inbox key for "no message": below every real record key (merge_key >= 0
# for any non-ABSENT record; ABSENT maps to -1 and never wins).
NO_MESSAGE = jnp.int32(-1)


# --------------------------------------------------------------------------
# The wire-format bitfield ladder
# --------------------------------------------------------------------------
#
# Every wire key is one signed integer word laid out
#
#   [sign 0] [dead] [epoch (E bits, open-world only)] [incarnation] [suspect]
#
# with the dead bit on top so the inbox max-fold keeps the reference's
# DEAD-absorbs order (records.merge_key docstring), a higher epoch
# ordering above any incarnation of an older occupant within a liveness
# class (cross-epoch SEMANTICS live in :func:`merge_inbox`'s gate, not
# the fold), then incarnation, then the suspect bit breaking ties at
# equal incarnation.  The three rungs differ in where the dead bit sits
# — i.e. how many bits the key spends — and in the word dtype:
#
#   wide    int32 word, dead bit 30: the default.  29 incarnation bits
#           (23 with the 6-bit epoch field) — saturation 2^29-1 / 2^23-1.
#   wire24  int32 word, dead bit 23: the compact-carry headroom rung.
#           The STORED table stays int16 (models/swim.SwimParams.
#           compact_carry) but the WIRE key widens from 16 to 24 bits
#           inside the int32 word already crossing ICI — epoch 2 -> 4
#           bits and the incarnation field grows to 22 / 18 bits, so the
#           int16 stored-incarnation ceiling (32767) becomes the binding
#           cap instead of the wire's 2^11-1 (models/swim._wire_inc_sat).
#   wire16  int16 word, dead bit 14 (records.merge_key16): the
#           capacity/bandwidth rung.  13 incarnation bits (11 with the
#           2-bit epoch field) — saturation 8191 / 2047.
#
# ALIVE/transmit flags are NOT a separate field: an ALIVE record is
# exactly a key with the dead and suspect bits clear (is_alive_key), so
# the fused single-buffer wire (models/swim.SwimParams.fused_wire)
# derives the merge gate's ALIVE flag from the folded winner key itself
# instead of shipping a parallel flag buffer — the flag bits ride inside
# the key word for free, for every rung of the ladder.


@dataclasses.dataclass(frozen=True)
class WireFormat:
    """One rung of the wire-format ladder (module-level layout comment).

    ``dead_bit`` fixes the whole layout: suspect at bit 0, incarnation
    at bits 1..(dead_bit-1-epoch_bits), the identity-epoch field (when
    the open-world plane is on) directly under the dead bit.  This
    table is the ONE source of truth for every saturation clamp in the
    tree — the self-refutation bump, the WIRE_SATURATION monitor bound,
    the compact-carry encode clamp all derive from :meth:`inc_sat`
    (grep-proofed by tests/test_wire_constants.py).
    """

    name: str
    dead_bit: int
    epoch_bits: int      # field width when the open-world plane is on
    wide_word: bool      # True: int32 wire word; False: int16

    @property
    def dtype(self):
        return jnp.int32 if self.wide_word else jnp.int16

    @property
    def word_bytes(self) -> int:
        return 4 if self.wide_word else 2

    def inc_bits(self, epoch_bits: int = 0) -> int:
        """Incarnation field width at the given active epoch width."""
        return self.dead_bit - 1 - epoch_bits

    def inc_sat(self, epoch_bits: int = 0) -> int:
        """Largest incarnation the key field carries exactly."""
        return (1 << self.inc_bits(epoch_bits)) - 1

    def epoch_cap(self) -> int:
        return (1 << self.epoch_bits) - 1


WIDE = WireFormat("wide", dead_bit=30, epoch_bits=6, wide_word=True)
WIRE24 = WireFormat("wire24", dead_bit=23, epoch_bits=4, wide_word=True)
WIRE16 = WireFormat("wire16", dead_bit=14, epoch_bits=2, wide_word=False)

WIRE_FORMATS = {f.name: f for f in (WIDE, WIRE24, WIRE16)}

# Back-compat aliases (the PR-10 epoch-bit constants, now table rows).
EPOCH_BITS_WIDE = WIDE.epoch_bits
EPOCH_BITS_COMPACT = WIRE16.epoch_bits


def resolve_format(compact: bool = False, fmt: WireFormat = None) -> WireFormat:
    """The active :class:`WireFormat`: an explicit ``fmt`` wins; the
    legacy ``compact`` bool selects wire16 vs wide (every pre-ladder
    call site and test keeps meaning exactly what it meant)."""
    if fmt is not None:
        return fmt
    return WIRE16 if compact else WIDE


def no_message(compact: bool = False, fmt: WireFormat = None):
    """The "no message" key in the wire dtype.

    Mixing the int32 constant into int16 expressions would silently
    promote whole buffers back to int32 — always take the constant from
    here when the key dtype is mode-dependent."""
    f = resolve_format(compact, fmt)
    return NO_MESSAGE if f.wide_word else jnp.int16(-1)


def pack_record(status, inc, compact: bool = False, epoch=None,
                epoch_bits: int = 0, fmt: WireFormat = None):
    """Pack (status, incarnation[, epoch]) into the merge key of the
    active wire format (the :class:`WireFormat` ladder; the
    epoch-extended layout when ``epoch_bits > 0`` — see the
    module-level layout comment).

    ABSENT packs to -1 == no_message(...): absent entries are simply
    never transmitted, matching the reference where only table-present
    records go into SYNC/gossip payloads
    (MembershipProtocolImpl.java:446-454).
    """
    f = resolve_format(compact, fmt)
    if epoch_bits == 0:
        # The two legacy rungs delegate to the records.py key builders
        # (byte-for-byte the pre-ladder wire).
        if f is WIRE16:
            return records.merge_key16(status, inc)
        if f is WIDE:
            return records.merge_key(status, inc)
    status = jnp.asarray(status)
    inc = jnp.asarray(inc, dtype=jnp.int32)
    inc_bits = f.inc_bits(epoch_bits)
    is_dead = (status == records.DEAD).astype(jnp.int32)
    is_suspect = (status == records.SUSPECT).astype(jnp.int32)
    inc_sat = jnp.minimum(inc, jnp.int32((1 << inc_bits) - 1))
    # At epoch_bits == 0 (wire24's flat layout reaches this generic
    # branch) the epoch field has ZERO width: clip to 0, never let a
    # passed epoch value shift into the dead bit.
    ep = jnp.asarray(0 if epoch is None else epoch, jnp.int32)
    ep = jnp.clip(ep, 0, (1 << epoch_bits) - 1)
    key = ((is_dead << f.dead_bit) | (ep << (inc_bits + 1))
           | (inc_sat << 1) | is_suspect)
    key = jnp.where(status == records.ABSENT, -1, key)
    return key.astype(f.dtype)


def unpack_record(key, compact: bool = False, epoch_bits: int = 0,
                  fmt: WireFormat = None):
    """Invert :func:`pack_record`: key -> (status int8, incarnation int32).

    Keys < 0 unpack to (ABSENT, 0).  The epoch field (when
    ``epoch_bits > 0``) is read separately by :func:`unpack_epoch` so
    the dominant two-field call sites stay unchanged.
    """
    f = resolve_format(compact, fmt)
    inc_mask = (1 << f.inc_bits(epoch_bits)) - 1
    key = jnp.asarray(key, dtype=jnp.int32)
    is_dead = (key >> f.dead_bit) & 1
    is_suspect = key & 1
    status = jnp.where(
        is_dead == 1,
        records.DEAD,
        jnp.where(is_suspect == 1, records.SUSPECT, records.ALIVE),
    )
    status = jnp.where(key < 0, records.ABSENT, status).astype(jnp.int8)
    inc = jnp.where(key < 0, 0, (key >> 1) & inc_mask).astype(jnp.int32)
    return status, inc


def unpack_epoch(key, compact: bool = False, epoch_bits: int = 0,
                 fmt: WireFormat = None):
    """The identity-epoch field of an epoch-extended key (int32; keys
    < 0 — no message / ABSENT — unpack to epoch 0)."""
    if epoch_bits == 0:
        return jnp.zeros_like(jnp.asarray(key, jnp.int32))
    f = resolve_format(compact, fmt)
    inc_bits = f.inc_bits(epoch_bits)
    key = jnp.asarray(key, dtype=jnp.int32)
    ep = (key >> (inc_bits + 1)) & ((1 << epoch_bits) - 1)
    return jnp.where(key < 0, 0, ep).astype(jnp.int32)


def is_alive_key(key, compact: bool = False, fmt: WireFormat = None):
    """True where ``key`` packs an ALIVE record (dead/suspect bits clear).

    The ALIVE-gate side channel must reflect the *transmitted* record, not
    the sender's table status — they differ for a graceful leaver, whose
    final-round gossip carries DEAD@inc+1 while its own table row is
    pinned ALIVE (models/swim._send_payloads).  An ABSENT entry must not
    open for that DEAD notice (MembershipRecord.java:67-69).

    This is also the FUSED wire's merge gate (models/swim.SwimParams.
    fused_wire): applied to the round's folded winner key it yields the
    winner's own ALIVE flag — no parallel flag buffer needs to cross
    the wire, because the flag is a pure function of the key bits.
    """
    f = resolve_format(compact, fmt)
    key = jnp.asarray(key)
    return (key >= 0) & (((key >> f.dead_bit) & 1) == 0) & ((key & 1) == 0)


def scatter_max(values, targets, drop, n_rows: int):
    """Deliver each sender's record row to its targets; inbox = per-cell max.

    Args:
      values:  ``[S, K]`` int32 packed record keys per sender (NO_MESSAGE for
               slots the sender does not transmit).
      targets: ``[S, F]`` int32 receiver row indices per sender (global).
      drop:    ``[S, F]`` bool, True = message lost in flight (the
               NetworkEmulator seam, reference NetworkEmulator.java:132-192).
      n_rows:  global receiver count (inbox height).

    Returns ``[n_rows, K]`` int32 inbox: the max packed key received per
    (receiver, subject), NO_MESSAGE where nothing arrived.

    The fanout axis is unrolled (F is 3-4, reference gossipFanout default
    ClusterConfig.java:34-36); each step is one XLA scatter-max, which TPU
    lowers natively; duplicate-index collisions combine associatively.
    """
    n_fanout = targets.shape[1]
    no_msg = values.dtype.type(-1)  # key dtype follows the wire format
    inbox = jnp.full((n_rows, values.shape[1]), no_msg, dtype=values.dtype)
    for f in range(n_fanout):
        contribution = jnp.where(drop[:, f, None], no_msg, values)
        inbox = inbox.at[targets[:, f]].max(contribution, mode="drop")
    return inbox


def scatter_or(flags, targets, drop, n_rows: int):
    """Boolean variant of :func:`scatter_max`: inbox = any sender flagged.

    Used for the ALIVE-gate side channel (records.merge_inbound's null-gate:
    an ABSENT entry opens only for an ALIVE record,
    MembershipRecord.java:67-69), and for pure infection bits in the
    gossip-only model.
    """
    n_fanout = targets.shape[1]
    inbox = jnp.zeros((n_rows, flags.shape[1]), dtype=jnp.bool_)
    for f in range(n_fanout):
        contribution = flags & ~drop[:, f, None]
        inbox = inbox.at[targets[:, f]].max(contribution, mode="drop")
    return inbox


def wire_saturation(messages_sent, live_senders, fanout):
    """Wire-channel saturation: gossip messages actually sent this
    round over the channel's send-slot capacity (the health-registry
    gauge, telemetry/metrics.py).

    Capacity = live senders x fanout slots — every live member owns
    ``fanout`` gossip sends per round whether or not it has hot records
    (GossipProtocolImpl.java:211-237 batches all selected gossips into
    one message per target, so a sender's per-round wire budget is its
    fanout).  Saturation 0 = idle channel; 1 = every live member
    spreading every round, the dissemination-backlog ceiling.
    """
    cap = jnp.maximum(
        jnp.asarray(live_senders, jnp.float32)
        * jnp.asarray(fanout, jnp.float32),
        1.0,
    )
    return jnp.asarray(messages_sent, jnp.float32) / cap


def merge_inbox(entry_status, entry_inc, inbox_key, inbox_any_alive,
                compact: bool = False, suppress=None, entry_epoch=None,
                epoch_bits: int = 0, epoch_guard: bool = True,
                fmt: WireFormat = None):
    """Merge one round's inbox into the membership table rows.

    Equivalent to one valid arrival-order serialization of the reference's
    per-message ``updateMembership`` loop (MembershipProtocolImpl.java:475-541)
    — see records.merge_inbound for the argument; here the fold over inbound
    records already happened inside the scatter (max of packed keys), so only
    the entry-gate logic remains:

      - ABSENT entry: opens only if some ALIVE record arrived
        (``inbox_any_alive``); once open, the winner always applies (its key
        dominates the gate-opener's, and every >= -comparison in
        MembershipRecord.java:76-83 is monotone in the packed key).
      - live entry: standard ``is_overrides`` gate against the winner.

    Stored DEAD semantics: an accepted DEAD record *removes* the entry in the
    reference (MembershipProtocolImpl.java:512-516), so for merge gating a
    stored DEAD behaves like ABSENT (a later ALIVE at any incarnation is
    re-accepted — the deliberate no-tombstone design, SURVEY.md §5.3,
    exercised by MembershipProtocolTest.testRestartFailedMembers).  We keep
    the DEAD code + incarnation in the table anyway so death notices keep
    spreading for their remaining gossip periods (the reference's gossip
    component retransmits independently of the table,
    GossipProtocolImpl.java:239-250); transmission masks decide visibility.

    ``suppress`` (optional [..] bool, None = off): cells inside their
    dead-member suppression window (models/swim.SwimParams.
    dead_suppress_rounds) gate by their TRUE DEAD key instead of the
    ABSENT gate — nothing but a strictly higher DEAD key overrides, so
    a freshly stored tombstone does not reopen for an arriving ALIVE
    (of any incarnation: a suppressed reopen would re-hot the death
    notice and re-burn an incarnation, the exact ping-pong the window
    exists to break — models/sync.py "quiesced-heal precondition").
    After the window the cell gates like ABSENT again (the reference's
    remove-then-re-add recovery).

    Identity epochs (``epoch_bits > 0`` — the open-world plane,
    models/swim.SwimParams.open_world): ``entry_epoch`` is the stored
    cell's identity epoch and the winner's epoch unpacks from the key.
    With ``epoch_guard`` on (the plane's contract):

      - a LOWER-epoch winner is DROPPED — the previous occupant's
        tombstones and stale hot ALIVE notices cannot touch the new
        identity's record (the slot-recycling hazard this lane exists
        to kill);
      - a HIGHER-epoch winner is admitted only when it is ALIVE — the
        new identity enters through its own join announcement, exactly
        the ABSENT null-gate rule applied per identity
        (MembershipRecord.java:67-69), and the admission OVERRIDES the
        dead-member suppression window (a suppressed tombstone guards
        the OLD identity's death notice; it must not block a
        higher-epoch JOIN);
      - equal epochs gate exactly as before, on the epoch-stripped
        record keys.

    ``epoch_guard=False`` with ``epoch_bits > 0`` compares the FULL
    packed keys — epoch-blind precedence with the epoch field demoted
    to high incarnation bits.  The production naive-reuse control arm
    (models/swim.SwimParams.epoch_guard=False) instead drops the epoch
    field from the wire entirely (its ``epoch_bits`` property returns 0
    — the true reference layout, under which the old occupant's hot
    tombstone kills the new member and its stale higher-incarnation
    ALIVE notices shadow the dead identity; the invariant monitor
    proves those attribution-free by incarnation forensics,
    chaos/monitor.NO_RESURRECTION).  This branch exists for unit-level
    demonstrations of exactly what the guard changes on
    otherwise-identical keys (tests/test_open_world.py).

    Returns (status int8, inc int32, changed bool) when
    ``epoch_bits == 0`` (the exact pre-open-world contract), else
    (status int8, inc int32, epoch int32, changed bool).
    """
    f = resolve_format(compact, fmt)
    win_status, win_inc = unpack_record(inbox_key, epoch_bits=epoch_bits,
                                        fmt=f)

    # Stored DEAD gates like ABSENT (record was deleted in the reference).
    gate_status = jnp.where(entry_status == records.DEAD, records.ABSENT, entry_status)

    # The live-entry is_overrides gate IS the packed-key order (the same
    # monotonicity the inbox max-fold already relies on — records.merge_key
    # docstring): new DEAD's bit dominates any live key, higher incarnation
    # dominates the suspect bit, SUSPECT beats ALIVE at equal incarnation
    # via bit 0, and equal keys (no strict >) never override.  One compare
    # replaces the five-rule select chain in the hottest fusion; exact
    # below the key's incarnation saturation, where the fold itself
    # already lives.
    entry_key = pack_record(gate_status, entry_inc, fmt=f)
    # The ABSENT gate: only an ALIVE opener admits the winner (any
    # non-absent winner, i.e. key >= 0, once open).
    #
    # The strict > gate is exact only while incarnations stay at or
    # below the wire key's saturation point (8191 compact / 2^29-1
    # wide): above it, distinct incarnations pack to colliding keys and
    # the gate stops distinguishing records the int32 table still
    # could.  The invariant is enforced at the ONLY place incarnations
    # grow — the self-refutation bump clamps to the active wire's cap
    # (models/swim._wire_inc_sat) — and the at-the-cap merge behavior
    # is pinned by tests/test_wire16.py's saturation-boundary tests.
    absent = gate_status == records.ABSENT
    if epoch_bits == 0:
        accepts = jnp.where(
            absent, inbox_any_alive & (inbox_key >= 0), inbox_key > entry_key
        )
        if suppress is not None:
            # Suppressed tombstones keep their DEAD key in the gate: only
            # a strictly higher DEAD key overrides during the window.
            true_key = pack_record(entry_status, entry_inc, fmt=f)
            accepts = jnp.where(suppress, inbox_key > true_key, accepts)
        new_epoch = None
    else:
        entry_ep = jnp.asarray(entry_epoch, jnp.int32)
        win_ep = unpack_epoch(inbox_key, fmt=f,
                              epoch_bits=epoch_bits)
        if epoch_guard:
            # Same-epoch precedence on the epoch-STRIPPED keys (wide
            # int32 — the unpacked fields are already int32, and the
            # stripped compare never meets the int16 wire).
            entry_key0 = pack_record(gate_status, entry_inc)
            win_key0 = pack_record(win_status, win_inc)
            accepts = jnp.where(
                absent, inbox_any_alive & (inbox_key >= 0),
                win_key0 > entry_key0,
            )
            if suppress is not None:
                true_key0 = pack_record(entry_status, entry_inc)
                accepts = jnp.where(suppress, win_key0 > true_key0, accepts)
            # Cross-epoch: lower drops, higher admits only through the
            # new identity's own ALIVE (overriding any suppression —
            # the window guards the OLD identity's notice).
            accepts = jnp.where(
                win_ep > entry_ep, win_status == records.ALIVE,
                jnp.where(win_ep < entry_ep, False, accepts),
            )
        else:
            # Naive reuse (instrumented control): the reference's
            # epoch-blind precedence on the FULL packed keys; the epoch
            # field only rides along for attribution.
            entry_key_full = pack_record(gate_status, entry_inc,
                                         fmt=f, epoch=entry_ep,
                                         epoch_bits=epoch_bits)
            accepts = jnp.where(
                absent, inbox_any_alive & (inbox_key >= 0),
                inbox_key > entry_key_full,
            )
            if suppress is not None:
                true_key = pack_record(entry_status, entry_inc,
                                       fmt=f, epoch=entry_ep,
                                       epoch_bits=epoch_bits)
                accepts = jnp.where(suppress, inbox_key > true_key, accepts)
        new_epoch = jnp.where(accepts, win_ep, entry_ep).astype(jnp.int32)

    new_status = jnp.where(accepts, win_status, entry_status).astype(jnp.int8)
    new_inc = jnp.where(accepts, win_inc, entry_inc).astype(jnp.int32)
    changed = accepts & ((new_status != entry_status) | (new_inc != entry_inc))
    if new_epoch is None:
        return new_status, new_inc, changed
    changed = changed | (accepts & (new_epoch != jnp.asarray(
        entry_epoch, jnp.int32)))
    return new_status, new_inc, new_epoch, changed
