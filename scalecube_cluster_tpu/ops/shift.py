"""Cyclic-shift message delivery: the zero-scatter TPU transport fast path.

The exact-uniform delivery in ops/delivery.py scatters each sender's row to
random receivers — correct, but an arbitrary-index scatter/gather is the
one memory pattern TPUs are bad at (the XLA scatter path processes a few
hundred million elements/sec, ~3 orders below HBM bandwidth for contiguous
ops).  This module implements the same round-level exchange as contiguous
vector ops only:

  Each round draws a handful of random *shifts* ``s`` (one per send
  channel); channel ``c`` delivers sender ``i``'s row to receiver
  ``(i + s_c) mod N``.  The union of a few fresh random cyclic shifts per
  round is an expander-like random communication graph: over the protocol's
  dissemination window (``repeat_mult * log2 N`` rounds) a node's contact
  set is indistinguishable from per-node uniform draws for the statistics
  SWIM cares about (dissemination time, detection latency, false-positive
  rate) — validated against the exact-scatter mode and the event-driven
  oracle in tests/test_shift_mode.py and tests/test_cross_validation.py.

  Documented deviations from per-node uniform target draws
  (models/swim.py module docstring lists the full set):
    - within one round all nodes share the same ``F`` target offsets, so
      per-round in-degree is exactly ``F`` instead of Poisson(F);
    - channel shifts are drawn i.i.d., so two channels collide with
      probability ~F(F-1)/2/(N-1) per round; on such a round EVERY node
      duplicates one target simultaneously (a correlated analog of
      scatter mode's independent with-replacement collisions).  Duplicate
      delivery is harmless in both modes — the inbox combine is an
      idempotent max (ops/delivery.py) — but it slightly lowers the
      effective fanout on collision rounds, identically for all nodes.

A delivery or lookup by a traced shift is one ``dynamic_slice`` on a
doubled buffer — contiguous reads at full HBM bandwidth, which is what
makes the 1M-member round run in milliseconds (bench.py).

Reference seam: this replaces TransportImpl.send0's per-message TCP path
(transport/TransportImpl.java:257-269) the same way ops/delivery.py does —
one round of messages = one tensor exchange; loss/delay/block are applied
per (sender, receiver) pair by models/swim.link_eval.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def doubled(x: jnp.ndarray) -> jnp.ndarray:
    """Concatenate ``x`` with itself along axis 0 (shift lookup buffer).

    Double once, slice many: every shifted view of ``x`` is then a single
    contiguous ``dynamic_slice`` (see :func:`deliver` / :func:`look`).
    """
    return jnp.concatenate([x, x], axis=0)


def deliver(doubled_x: jnp.ndarray, shift, n: int) -> jnp.ndarray:
    """Receiver view of a send-by-shift: row ``j`` = sender ``(j - shift) % n``.

    ``doubled_x`` is ``doubled(values)`` for per-sender ``values`` of height
    ``n``; ``shift`` is a traced int32 in [0, n).
    """
    start = jnp.asarray(n, jnp.int32) - jnp.asarray(shift, jnp.int32)
    return jax.lax.dynamic_slice_in_dim(doubled_x, start, n, axis=0)


def look(doubled_x: jnp.ndarray, shift, n: int) -> jnp.ndarray:
    """Sender view of its target's attribute: row ``i`` = ``x[(i + shift) % n]``.

    The dual of :func:`deliver`: where deliver moves payloads forward along
    the shift, look reads the *target's* property (liveness, partition id,
    subject slot) back at the sender.
    """
    return jax.lax.dynamic_slice_in_dim(
        doubled_x, jnp.asarray(shift, jnp.int32), n, axis=0
    )


class ShiftEngine:
    """Global-cyclic-shift delivery, single-device or row-sharded.

    Single device: the doubled-buffer dynamic-slice fast path above.

    Sharded (``axis_name`` set): rows are split into ``n_devices``
    contiguous blocks of ``n_local``; a global shift ``s = d*L + r``
    means receiver block ``m`` needs sender rows from blocks ``m-d`` and
    ``m-d-1``.  Those two blocks arrive via block-rotation collectives —
    ``lax.switch`` over the ``n_devices`` static ``ppermute`` rotations
    (a ppermute's permutation must be static; the switch makes the rotation
    amount data-dependent) — then one concat + dynamic-slice finishes the
    roll.  Per delivered array that is 2 ppermutes of one [L, ...] block
    over ICI — the neighbor-exchange analog of the scatter path's
    full-height pmax (parallel/mesh.py), moving O(L·K) per channel instead
    of O(N·K).

    Replicated arrays (world vectors: liveness, partition ids, node ids)
    skip the collectives entirely: every device holds the full height, so
    a shifted view is a plain doubled-slice at the device's row offset.
    """

    def __init__(self, n: int, offset=0, axis_name=None, n_devices: int = 1,
                 n_local: int = None, roll_payloads: bool = False):
        self.n = n
        self.offset = offset            # traced scalar under shard_map
        self.axis_name = axis_name
        self.n_devices = n_devices
        self.n_local = n if n_local is None else n_local
        # Single-device payload delivery normally doubles the buffer once
        # ([2N, K]) and slices per channel; the doubled copy is
        # PERSISTENT across the whole round.  ``roll_payloads`` trades it
        # for a jnp.roll per channel (two slices + concat, a transient
        # [N, K] the consumer fuses), value-identical:
        # roll(x, s)[j] == doubled(x)[n - s + j] == x[(j - s) % n].
        # Measured ~equal speed at full-view 26,624 (100.8 vs 101.4
        # ms/round) and did NOT move the capacity ceiling — the 28,672
        # boundary is compile-stage, not HBM (RESULTS.md round-4 log).
        # Sharded payloads never double (blocks travel by ppermute), so
        # the flag only affects the axis_name=None path.
        self.roll_payloads = roll_payloads

    # -- replicated world vectors ([N] on every device) -------------------

    def prep_replicated(self, x_full):
        return doubled(x_full)

    def look_replicated(self, dx, shift):
        """Local senders' view of target attribute: x[(off + l + s) % n]."""
        start = jnp.asarray(self.offset + shift, jnp.int32)
        return jax.lax.dynamic_slice_in_dim(dx, start, self.n_local, axis=0)

    def deliver_replicated(self, dx, shift):
        """Local receivers' view of sender attribute: x[(off + l - s) % n]."""
        start = jnp.asarray(self.n + self.offset - shift, jnp.int32)
        return jax.lax.dynamic_slice_in_dim(dx, start, self.n_local, axis=0)

    # -- sharded payloads ([n_local, ...] row slice per device) -----------

    def prep(self, x_local):
        if self.axis_name is None:
            return x_local if self.roll_payloads else doubled(x_local)
        return x_local

    def _rotate_blocks(self, x_local, d_blocks):
        """Device m ends up holding device (m - d_blocks) % M's block."""
        if self.n_devices == 1:
            return x_local

        def rotation(k):
            perm = [(j, (j + k) % self.n_devices)
                    for j in range(self.n_devices)]
            return lambda x: jax.lax.ppermute(x, self.axis_name, perm)

        branches = [rotation(k) for k in range(self.n_devices)]
        return jax.lax.switch(d_blocks % self.n_devices, branches, x_local)

    def deliver(self, h, shift):
        """Receiver row l gets sender row (off + l - shift) % n."""
        if self.axis_name is None:
            if self.roll_payloads:
                return jnp.roll(h, jnp.asarray(shift, jnp.int32), axis=0)
            return deliver(h, shift, self.n)
        ll = self.n_local
        d_blocks = shift // ll
        r = shift % ll
        x_a = self._rotate_blocks(h, d_blocks)          # block (m - d)
        x_b = self._rotate_blocks(h, d_blocks + 1)      # block (m - d - 1)
        both = jnp.concatenate([x_b, x_a], axis=0)      # rows of blocks
        return jax.lax.dynamic_slice_in_dim(both, ll - r, ll, axis=0)
