"""Cyclic-shift message delivery: the zero-scatter TPU transport fast path.

The exact-uniform delivery in ops/delivery.py scatters each sender's row to
random receivers — correct, but an arbitrary-index scatter/gather is the
one memory pattern TPUs are bad at (the XLA scatter path processes a few
hundred million elements/sec, ~3 orders below HBM bandwidth for contiguous
ops).  This module implements the same round-level exchange as contiguous
vector ops only:

  Each round draws a handful of random *shifts* ``s`` (one per send
  channel); channel ``c`` delivers sender ``i``'s row to receiver
  ``(i + s_c) mod N``.  The union of a few fresh random cyclic shifts per
  round is an expander-like random communication graph: over the protocol's
  dissemination window (``repeat_mult * log2 N`` rounds) a node's contact
  set is indistinguishable from per-node uniform draws for the statistics
  SWIM cares about (dissemination time, detection latency, false-positive
  rate) — validated against the exact-scatter mode and the event-driven
  oracle in tests/test_shift_mode.py and tests/test_cross_validation.py.

  Documented deviations from per-node uniform target draws
  (models/swim.py module docstring lists the full set):
    - within one round all nodes share the same ``F`` target offsets, so
      per-round in-degree is exactly ``F`` instead of Poisson(F);
    - a node cannot pick the same target twice in one round (shifts are
      drawn per channel), matching the reference's distinct-targets rule
      *better* than the with-replacement scatter mode does.

A delivery or lookup by a traced shift is one ``dynamic_slice`` on a
doubled buffer — contiguous reads at full HBM bandwidth, which is what
makes the 1M-member round run in milliseconds (bench.py).

Reference seam: this replaces TransportImpl.send0's per-message TCP path
(transport/TransportImpl.java:257-269) the same way ops/delivery.py does —
one round of messages = one tensor exchange; loss/delay/block are applied
per (sender, receiver) pair by models/swim.link_eval.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def doubled(x: jnp.ndarray) -> jnp.ndarray:
    """Concatenate ``x`` with itself along axis 0 (shift lookup buffer).

    Double once, slice many: every shifted view of ``x`` is then a single
    contiguous ``dynamic_slice`` (see :func:`deliver` / :func:`look`).
    """
    return jnp.concatenate([x, x], axis=0)


def deliver(doubled_x: jnp.ndarray, shift, n: int) -> jnp.ndarray:
    """Receiver view of a send-by-shift: row ``j`` = sender ``(j - shift) % n``.

    ``doubled_x`` is ``doubled(values)`` for per-sender ``values`` of height
    ``n``; ``shift`` is a traced int32 in [0, n).
    """
    start = jnp.asarray(n, jnp.int32) - jnp.asarray(shift, jnp.int32)
    return jax.lax.dynamic_slice_in_dim(doubled_x, start, n, axis=0)


def look(doubled_x: jnp.ndarray, shift, n: int) -> jnp.ndarray:
    """Sender view of its target's attribute: row ``i`` = ``x[(i + shift) % n]``.

    The dual of :func:`deliver`: where deliver moves payloads forward along
    the shift, look reads the *target's* property (liveness, partition id,
    subject slot) back at the sender.
    """
    return jax.lax.dynamic_slice_in_dim(
        doubled_x, jnp.asarray(shift, jnp.int32), n, axis=0
    )
