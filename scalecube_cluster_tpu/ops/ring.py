"""Delayed-delivery ring primitives, shared by the swim and gossip models.

The NetworkEmulator delays every message by an exponential draw
(transport/NetworkLinkSettings.java:64-74); on the round-quantized tick a
message's delay becomes a round offset ``floor(delay / round_ms)``,
saturating at the ring depth (documented saturation, not loss).  The ring
is a ``[D, N, ...]`` carry buffer: slot ``round % D`` holds the messages
due in that round; reading a round's slot clears it for reuse.

One implementation here, three users: models/swim.py (int32 record-key
ring + int8 ALIVE-flag ring), models/gossip.py (bool infection ring) —
keeping the slot arithmetic and saturation rule in a single place.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def delay_bins(key, mean_ms, round_ms: float, max_delay_rounds: int, shape):
    """Quantized round offset per message: floor(Exp(mean)/round), clamped.

    ``mean_ms`` broadcasts against ``shape`` (per-link means from
    models/swim.link_eval).
    """
    u = jax.random.uniform(key, shape)
    d_ms = -jnp.log1p(-u) * mean_ms
    q = jnp.floor(d_ms / round_ms).astype(jnp.int32)
    return jnp.clip(q, 0, max_delay_rounds)


def open_slot(ring, slot0, fill_value):
    """(due-now slice, ring with that slot reset to ``fill_value``)."""
    now = jax.lax.dynamic_index_in_dim(ring, slot0, axis=0, keepdims=False)
    cleared = jax.lax.dynamic_update_index_in_dim(
        ring, jnp.full_like(now, fill_value), slot0, axis=0
    )
    return now, cleared


def push_max(ring, slot, values):
    """Max-merge ``values`` into ring slot ``slot`` (record keys)."""
    cur = jax.lax.dynamic_index_in_dim(ring, slot, axis=0, keepdims=False)
    return jax.lax.dynamic_update_index_in_dim(
        ring, jnp.maximum(cur, values), slot, axis=0
    )


def push_or(ring, slot, values):
    """Or-merge ``values`` into ring slot ``slot`` (flag/infection bits)."""
    cur = jax.lax.dynamic_index_in_dim(ring, slot, axis=0, keepdims=False)
    return jax.lax.dynamic_update_index_in_dim(
        ring, cur | values, slot, axis=0
    )
