"""scalecube_cluster_tpu — a TPU-native SWIM membership framework.

A from-scratch reimplementation of the capabilities of ScaleCube Cluster
(reference: /root/reference, Java/Reactor/Netty) as a batched simulation
engine on TPU: per-node protocol state lives in sharded ``[N, ...]`` JAX
arrays, message delivery is a dense inbox-tensor exchange, and the whole
SWIM tick (random-probe failure detection, infection-style gossip,
suspicion timeouts with incarnation refutation, SYNC anti-entropy) runs
as one ``jax.lax.scan`` over protocol rounds under pjit/shard_map.

Layout (mirrors SURVEY.md §7):
  - ``records``    core record/merge semantics (MembershipRecord.isOverrides)
  - ``swim_math``  the analytic SWIM/gossip model (ClusterMath port)
  - ``config``     ClusterConfig with LAN/WAN/LOCAL presets
  - ``oracle``     event-driven small-N simulator (behavioral oracle,
                   stands in for the reference's in-JVM multi-node harness)
  - ``models``     the TPU tick functions (fd-only, gossip-only, full SWIM)
  - ``ops``        dense delivery / merge kernels: scatter-max inbox,
                   cyclic-shift fast path, counter-based PRNG
  - ``sweep``      vmap hyperparameter sweeps + curve artifacts
  - ``parallel``   mesh + sharding layer (row-sharded N over devices)
  - ``utils``      on-disk checkpointing + run logging for long scans
"""

from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.records import MemberStatus
from scalecube_cluster_tpu import swim_math

__version__ = "0.1.0"

__all__ = ["ClusterConfig", "MemberStatus", "swim_math", "__version__"]
