"""Closed-form SWIM/gossip analytic model.

Functional port of the reference's ``ClusterMath``
(cluster/src/main/java/io/scalecube/cluster/ClusterMath.java:8-136) — the
"published" performance model of the reference, used there both by the
runtime (suspicion timeout, gossip spread/sweep periods) and by tests as
the measurement oracle.  This repo uses it the same two ways: the TPU tick
derives its round budgets from it, and the validation suite checks measured
dissemination/convergence curves against it (BASELINE.md targets: within 5%).

All functions are pure Python on ints/floats; ``ceil_log2_jnp`` is the
traceable variant for use inside jitted code.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def ceil_log2(num: int) -> int:
    """``32 - numberOfLeadingZeros(num)`` == ``ceil(log2(num + 1))``.

    Reference: ClusterMath.java:133-135.  Examples: 0->0, 1->1, 2->2, 3->2,
    4->3, 50->6, 1000->10.
    """
    if num < 0:
        raise ValueError("num must be non-negative")
    return int(num).bit_length()


def ceil_log2_jnp(num):
    """Traceable ``ceil_log2`` for int32 arrays (uses count-leading-zeros)."""
    return 32 - jax.lax.clz(jnp.asarray(num, dtype=jnp.int32))


def gossip_convergence_probability(
    fanout: int, repeat_mult: int, cluster_size: int, loss: float
) -> float:
    """P(gossip reaches everyone) — ClusterMath.java:38-43.

    ``loss`` is a probability in [0, 1].
    """
    fanout_with_loss = (1.0 - loss) * fanout
    spread_size = cluster_size - cluster_size ** -(fanout_with_loss * repeat_mult - 2)
    return spread_size / cluster_size


def gossip_convergence_percent(
    fanout: int, repeat_mult: int, cluster_size: int, loss_percent: float
) -> float:
    """Convergence probability in percent — ClusterMath.java:23-28."""
    return gossip_convergence_probability(fanout, repeat_mult, cluster_size, loss_percent / 100.0) * 100.0


def max_messages_per_gossip_per_node(fanout: int, repeat_mult: int, cluster_size: int) -> int:
    """``fanout * repeatMult * ceilLog2(n)`` — ClusterMath.java:65-67."""
    return fanout * repeat_mult * ceil_log2(cluster_size)


def max_messages_per_gossip_total(fanout: int, repeat_mult: int, cluster_size: int) -> int:
    """``n * perNode`` — ClusterMath.java:53-55."""
    return cluster_size * max_messages_per_gossip_per_node(fanout, repeat_mult, cluster_size)


def gossip_periods_to_spread(repeat_mult: int, cluster_size: int) -> int:
    """How many gossip periods a node retransmits a gossip — ClusterMath.java:111-113."""
    return repeat_mult * ceil_log2(cluster_size)


def gossip_periods_to_sweep(repeat_mult: int, cluster_size: int) -> int:
    """Periods after which a gossip is garbage-collected — ClusterMath.java:99-103."""
    return 2 * (gossip_periods_to_spread(repeat_mult, cluster_size) + 1)


def gossip_dissemination_time(repeat_mult: int, cluster_size: int, gossip_interval_ms: int) -> int:
    """Spread periods x interval, in ms — ClusterMath.java:77-79."""
    return gossip_periods_to_spread(repeat_mult, cluster_size) * gossip_interval_ms


def gossip_timeout_to_sweep(repeat_mult: int, cluster_size: int, gossip_interval_ms: int) -> int:
    """Sweep periods x interval, in ms — ClusterMath.java:86-90."""
    return gossip_periods_to_sweep(repeat_mult, cluster_size) * gossip_interval_ms


def suspicion_timeout(suspicion_mult: int, cluster_size: int, ping_interval_ms: int) -> int:
    """``suspicionMult * ceilLog2(n) * pingInterval`` — ClusterMath.java:123-125."""
    return suspicion_mult * ceil_log2(cluster_size) * ping_interval_ms


# ---------------------------------------------------------------------------
# Failure-detector false-positive model (this repo's extension)
# ---------------------------------------------------------------------------
#
# The reference's ClusterMath covers gossip; its FD has no closed-form
# analog even though its tests measure FD behavior (FailureDetectorTest).
# The TPU tick's probe collapse (models/swim._chain_ok: one Bernoulli per
# chain against the product of per-hop delivery probabilities — exact for
# independent per-hop losses) makes the per-probe false-suspicion
# probability computable, which is what lets the measured
# first-false-positive curve be validated quantitatively (BASELINE.md
# north star; tests/test_scaling_curves.py, experiments/fp_curve.py).


def fd_false_suspect_probability(loss: float, ping_req_members: int,
                                 cluster_size: int) -> float:
    """P(one probe of a LIVE member yields a SUSPECT verdict) under
    symmetric i.i.d. per-message loss.

    The probe (FailureDetectorImpl.java:128-213, collapsed in
    models/swim 3.2-phase form) fails only if the 2-hop direct ping
    chain drops AND every one of the ``ping_req_members`` 4-hop proxy
    chains drops:

      P = (1 - (1-p)^2) * prod_r (1 - (1 - 1/(n-1)) * (1-p)^4)

    The ``1/(n-1)`` term is the probability a uniformly drawn proxy
    collides with the target (a proxy cannot rescue its own probe;
    both delivery modes exclude that chain — models/swim.py
    ``proxies != t`` / ``ps != fd_shift``).
    """
    p = float(loss)
    n = cluster_size
    direct_fail = 1.0 - (1.0 - p) ** 2
    proxy_rescue = (1.0 - 1.0 / (n - 1)) * (1.0 - p) ** 4
    return direct_fail * (1.0 - proxy_rescue) ** ping_req_members


def fd_expected_false_onsets(loss: float, ping_req_members: int,
                             cluster_size: int, fd_rounds: int) -> float:
    """Expected first-false-suspicion events in an FD-only run.

    Setup (models/fd.py isolation, warm full view, everyone live,
    suspicion horizon > run length): each live observer probes one
    uniformly chosen known-live entry per fd round, so a given
    (observer, subject) entry is probed with probability 1/(n-1) per fd
    round and transitions ALIVE -> SUSPECT exactly once (nothing refutes
    or kills it).  Each of the n*(n-1) entries is an absorbing 2-state
    chain:

      E[onsets] = n * (n-1) * (1 - (1 - P_fs/(n-1))^fd_rounds)
    """
    n = cluster_size
    q = fd_false_suspect_probability(loss, ping_req_members, n) / (n - 1)
    return n * (n - 1) * (1.0 - (1.0 - q) ** fd_rounds)
