"""Declarative, composable fault-scenario DSL + seeded campaign generator.

A :class:`Scenario` is a named list of fault OPS over an N-member world
and a horizon; ``Scenario.build(params)`` compiles the ops to the
existing dense fault schedules — ``SwimWorld``'s crash/leave/partition
arrays and ``LinkFaults`` rules (models/swim.py) — plus the
:class:`~scalecube_cluster_tpu.chaos.monitor.MonitorSpec` that tells
the in-jit invariant monitor what the scenario promises:

  - ``check_false_suspicion`` is on only for PRISTINE networks (no
    loss, no link rules, no delays, no partitions): there, any new
    suspicion of a live subject is a safety violation;
  - per-subject completeness deadlines ``complete_by`` are derived
    from the compiled schedules: a permanently crashed/left subject
    must be dropped by every eligible observer within
    :func:`completeness_bound` rounds of max(its fault round, the end
    of the last network disruption).  Scenarios containing a PERMANENT
    network disruption (a forever block/loss rule) make no completeness
    promise — the disruption can legitimately isolate an observer.

Ops (each is a frozen dataclass; ``apply(world, n, horizon)`` composes
on the world builders, so op order is schedule-override order):

  Crash / CrashBurst    process crash (single node / correlated set),
                        optionally revived — ``SwimWorld.with_crash``.
  Leave                 graceful leave — ``with_leave``.
  ChurnStorm            staggered crash(/revive) waves over a node
                        pool: wave w crashes its slice at
                        ``start_round + w * wave_every``.
  LinkLoss              one loss/delay rule — ``with_link_fault``.
  FlappingLink          a link that goes fully down/up in cycles
                        (n_cycles loss-1.0 windows).
  Brownout              asymmetric range-to-range loss ramp: loss
                        steps up to ``peak_loss``, holds, steps down.
  RollingPartition      rotating split phases with re-heal phases in
                        between, compiled to the ``partition_of``
                        rolling schedule (explicit zero tail past the
                        horizon so the cycle cannot wrap back into a
                        split).

Campaign generation: :func:`generate_scenario` is a PURE function of
(seed, n, severity) — any failing scenario in a campaign is the
one-line repro ``generate_scenario(seed=S, n=N, severity='tier')``.
Severity tiers (:data:`SEVERITIES`): ``mild`` = one clean process or
link fault on a lossless network; ``moderate`` = background wire loss
plus two composed faults (bursts, churn, flaps, brownouts); ``severe``
= rolling partitions + churn storms + brownouts over a lossy network.

Compile hygiene: generated horizons are quantized (multiples of 64)
and ``LinkFaults`` rule counts padded to a fixed width with
match-nothing rules, so a campaign of many scenarios reuses a handful
of compiled programs instead of one per scenario.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from scalecube_cluster_tpu.chaos.monitor import MonitorSpec
from scalecube_cluster_tpu.models import metadata, swim

INT32_MAX = int(jnp.iinfo(jnp.int32).max)

SEVERITIES = ("mild", "moderate", "severe")

# Fixed LinkFaults width generated scenarios pad to (match-nothing
# rules are free: an empty id range matches no message).
_RULE_PAD = 8
_HORIZON_QUANTUM = 64


def quiesce_bound(params: "swim.SwimParams", n: int) -> int:
    """Rounds a fault's effects need to go COLD: cross-fault suspicions
    detected and spread, suspicion timers matured to tombstones, and the
    tombstones' gossip windows expired.  A partition healed (or a node
    revived) after at least this many rounds re-converges monotonically
    under the SYNC anti-entropy plane; a shorter window releases
    freshly-hot tombstones into the healed cluster, a regime the merge
    precedence cannot bound (models/sync.py "quiesced-heal
    precondition")."""
    log2n = math.ceil(math.log2(n + 1))
    return (24 * max(1, params.ping_every)      # detection + verdict spread
            + params.suspicion_rounds           # timers mature
            + params.periods_to_spread + 1      # tombstone gossip expires
            + 4 * log2n + 16)


def post_heal_agreement_bound(params: "swim.SwimParams", n: int) -> int:
    """Rounds after the last heal within which every live table must
    agree (the POST_HEAL_DIVERGENCE window): one anti-entropy exchange
    interval + the dissemination bound for the reopened records + probe
    slack for in-flight FD refute pushes.  The ISSUE's
    ``sync_interval + dissemination_bound`` contract, deliberately
    generous — it is a convergence CONTRACT, not a latency benchmark
    (``bench.py --sync`` measures the actual figure)."""
    log2n = math.ceil(math.log2(n + 1))
    return (params.sync_interval
            + 4 * log2n + params.periods_to_spread
            + 2 * max(1, params.ping_every) + 16)


def metadata_convergence_bound(params: "swim.SwimParams", n: int) -> int:
    """Rounds within which a pushed metadata word must reach every live
    table: one anti-entropy exchange interval (the full-table lane that
    crosses healed partitions — models/metadata.py) + the piggyback
    dissemination bound for the hot window + probe slack.  Like
    :func:`post_heal_agreement_bound` this is a convergence CONTRACT,
    deliberately generous — ``bench.py --rollout`` measures the actual
    p99 (``metadata_convergence_p99``); the staged-rollout gate and the
    telemetry regress consume THIS bound so the deadline arithmetic
    lives in one place."""
    log2n = math.ceil(math.log2(n + 1))
    return (params.sync_interval
            + 4 * log2n + params.periods_to_spread
            + 2 * max(1, params.ping_every) + 16)


def quiesced_heal_scenario(params: "swim.SwimParams", n: int,
                           name: str = "quiesced-heal",
                           slack: int = 32) -> "Scenario":
    """The canonical single split/heal cycle sized to QUIESCE: one
    RollingPartition whose split clears :func:`quiesce_bound` (rounded
    up to the 16-round phase quantum) and whose horizon covers the heal
    plus one :func:`post_heal_agreement_bound` window plus ``slack`` —
    the schedule ``bench.py --sync``, the monitor tests, and the oracle
    partition cross-validation all measure, built in ONE place so the
    bound arithmetic cannot drift between them.  The split length is
    exposed as ``ops[0].phase_rounds`` (= the heal round)."""
    phase = -(-quiesce_bound(params, n) // 16) * 16
    horizon = 2 * phase + post_heal_agreement_bound(params, n) + slack
    return Scenario(
        name=name, n_members=n, horizon=horizon,
        ops=(RollingPartition(from_round=0, phase_rounds=phase,
                              n_cycles=1),),
    )


def completeness_bound(params: "swim.SwimParams", n: int) -> int:
    """Rounds within which a permanent crash/leave must be DEAD in every
    eligible observer's view: detection slack (FD probe discovery has a
    geometric tail over target draws) + the suspicion timeout +
    dissemination/anti-entropy slack.  Deliberately generous — the
    monitor's completeness check is a liveness CONTRACT, not a latency
    benchmark (the latency histograms in telemetry/ measure that)."""
    log2n = math.ceil(math.log2(n + 1))
    return (params.suspicion_rounds
            + 24 * max(1, params.ping_every)
            + 4 * log2n
            + 2 * max(1, params.sync_every)
            + 16)


# --------------------------------------------------------------------------
# Ops
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Crash:
    """Crash ``node`` during [at_round, until_round); INT32_MAX = forever."""

    node: int
    at_round: int
    until_round: int = INT32_MAX

    def apply(self, world, n, horizon):
        return world.with_crash(self.node, self.at_round, self.until_round)

    def disruption(self, n, horizon):
        return None                      # process fault, not network


@dataclasses.dataclass(frozen=True)
class CrashBurst:
    """Correlated burst: every node in ``nodes`` crashes at the same
    round (revived together when ``until_round`` is finite)."""

    nodes: Tuple[int, ...]
    at_round: int
    until_round: int = INT32_MAX

    def apply(self, world, n, horizon):
        return world.with_crash(list(self.nodes), self.at_round,
                                self.until_round)

    def disruption(self, n, horizon):
        return None


@dataclasses.dataclass(frozen=True)
class Leave:
    """Graceful leave at ``at_round`` (DEAD@inc+1 self-gossip, then down)."""

    node: int
    at_round: int

    def apply(self, world, n, horizon):
        return world.with_leave(self.node, self.at_round)

    def disruption(self, n, horizon):
        return None


@dataclasses.dataclass(frozen=True)
class ChurnStorm:
    """Staggered crash(/revive) waves: wave w crashes
    ``nodes[w*wave_size:(w+1)*wave_size]`` at
    ``start_round + w*wave_every``, each down for ``down_rounds``
    (0 = permanent).  Node slices are disjoint by construction, so
    waves never clobber each other's windows.

    Arrival waves (the open-world extension — SwimParams.open_world
    must be on for the joins to execute): ``join_wave_size > 0`` makes
    each wave ALSO admit that many NEW members (fresh identities) into
    recycled DEAD slots, ``join_lag`` rounds after the wave's crashes.
    Join targets drain a FIFO of free slots: the ``arrivals`` pool
    (slots crashed at round 0 — the pre-dead free capacity that makes
    NET-POSITIVE growth possible: joins - permanent crashes =
    n_waves*join_wave_size - len(nodes)) first, then each wave's own
    crashed slots once they are eligible (dead strictly before the
    join round).  Construction raises if a wave cannot fill its join
    quota — a storm that silently joined fewer members than declared
    would corrupt the growth arithmetic (scenarios stay exact, pure in
    their fields).  ``join_wave_size > 0`` requires permanent crashes
    (``down_rounds == 0``): a revive schedule and a join cannot share
    a slot."""

    nodes: Tuple[int, ...]
    wave_size: int
    start_round: int
    wave_every: int
    down_rounds: int = 0
    join_wave_size: int = 0
    join_lag: int = 0
    arrivals: Tuple[int, ...] = ()

    def __post_init__(self):
        if self.wave_size < 1 or len(self.nodes) % self.wave_size:
            raise ValueError(
                f"wave_size {self.wave_size} must divide the pool size "
                f"{len(self.nodes)}")
        if self.join_wave_size:
            if self.down_rounds:
                raise ValueError(
                    "ChurnStorm arrival waves need permanent crashes "
                    f"(down_rounds=0; got {self.down_rounds}) — a revive "
                    "schedule and a join cannot share a slot")
            if set(self.arrivals) & set(self.nodes):
                raise ValueError(
                    f"arrivals pool overlaps the crash pool: "
                    f"{sorted(set(self.arrivals) & set(self.nodes))}")
            self._join_schedule()        # validates quota feasibility

    @property
    def n_waves(self) -> int:
        return len(self.nodes) // self.wave_size

    def _join_schedule(self):
        """[(slot, join_round)] for every arrival, FIFO over free slots
        (class docstring); raises when a wave's quota cannot be met."""
        free = [(s, 0) for s in self.arrivals]       # (slot, death round)
        out = []
        for w in range(self.n_waves):
            at = self.start_round + w * self.wave_every
            join_at = at + self.join_lag
            free.extend(
                (s, at)
                for s in self.nodes[w * self.wave_size:
                                    (w + 1) * self.wave_size])
            taken = 0
            while taken < self.join_wave_size and free:
                slot, died = free[0]
                if died >= join_at:      # not yet dead at the join round
                    break
                free.pop(0)
                out.append((slot, join_at))
                taken += 1
            if taken < self.join_wave_size:
                raise ValueError(
                    f"ChurnStorm wave {w} can only fill {taken} of "
                    f"{self.join_wave_size} join slots at round "
                    f"{join_at} — grow the arrivals pool or the "
                    f"join_lag (free-slot FIFO exhausted)")
        return out

    def apply(self, world, n, horizon):
        if self.arrivals:
            world = world.with_crash(list(self.arrivals), 0)
        for w in range(self.n_waves):
            at = self.start_round + w * self.wave_every
            until = at + self.down_rounds if self.down_rounds else INT32_MAX
            world = world.with_crash(
                list(self.nodes[w * self.wave_size:(w + 1) * self.wave_size]),
                at, until)
        if self.join_wave_size:
            for slot, join_at in self._join_schedule():
                world = world.with_join(slot, join_at)
        return world

    def disruption(self, n, horizon):
        return None


@dataclasses.dataclass(frozen=True)
class Join:
    """Admit a NEW member into recycled DEAD ``slot`` at ``at_round``
    (``SwimWorld.with_join`` — the slot must be scheduled dead first;
    op order matters, like every schedule-override op).  Requires
    ``SwimParams.open_world`` to execute as an identity join."""

    slot: int
    at_round: int

    def apply(self, world, n, horizon):
        return world.with_join(self.slot, self.at_round)

    def disruption(self, n, horizon):
        return None                      # process-level, not network


@dataclasses.dataclass(frozen=True)
class LinkLoss:
    """One per-link loss/delay rule (``src``/``dst``: id or (lo, hi))."""

    src: object
    dst: object
    loss: float
    delay_ms: float = 0.0
    from_round: int = 0
    until_round: int = INT32_MAX

    def apply(self, world, n, horizon):
        return world.with_link_fault(self.src, self.dst, self.loss,
                                     self.delay_ms, self.from_round,
                                     self.until_round)

    def disruption(self, n, horizon):
        if self.loss > 0.0 or self.delay_ms > 0.0:
            return (self.from_round, self.until_round)
        return None


@dataclasses.dataclass(frozen=True)
class FlappingLink:
    """src→dst link flaps: ``n_cycles`` windows of ``down_rounds`` at
    ``loss`` (default full block), ``up_rounds`` healthy in between."""

    src: int
    dst: int
    from_round: int
    n_cycles: int
    down_rounds: int
    up_rounds: int
    loss: float = 1.0

    def __post_init__(self):
        if self.down_rounds < 1 or self.n_cycles < 1:
            raise ValueError(
                f"FlappingLink needs down_rounds >= 1 and n_cycles >= 1 "
                f"(got down_rounds={self.down_rounds}, "
                f"n_cycles={self.n_cycles}) — a flap with no down window "
                f"is no fault")

    def apply(self, world, n, horizon):
        period = self.down_rounds + self.up_rounds
        for c in range(self.n_cycles):
            start = self.from_round + c * period
            world = world.with_link_fault(
                self.src, self.dst, self.loss,
                from_round=start, until_round=start + self.down_rounds)
        return world

    def disruption(self, n, horizon):
        period = self.down_rounds + self.up_rounds
        end = (self.from_round + (self.n_cycles - 1) * period
               + self.down_rounds)
        return (self.from_round, end)


@dataclasses.dataclass(frozen=True)
class Brownout:
    """Asymmetric range-to-range degradation ramp: loss (and optionally
    mean link delay) steps ``peak/steps .. peak`` over ``ramp_rounds``,
    holds at the peak for ``hold_rounds`` (0 = ramp straight back
    down), then steps back down — up to 2*steps+1 rules (zero-length
    windows are skipped, not emitted).

    ``peak_delay_ms`` (default 0 = pure loss, the original op) ramps a
    mean exponential per-hop delay alongside the loss — the slow-link /
    slow-host brownout whose probe failures are TIMEOUTS rather than
    drops (the regime Lifeguard's LHA timeout scaling targets,
    models/lifeguard.py; link delay enters the FD hop budgets exactly,
    models/swim._chain_ok)."""

    src: Tuple[int, int]
    dst: Tuple[int, int]
    peak_loss: float
    from_round: int
    ramp_rounds: int
    hold_rounds: int
    steps: int = 3
    peak_delay_ms: float = 0.0

    def __post_init__(self):
        if self.steps < 1 or self.ramp_rounds < 1:
            raise ValueError(
                f"Brownout needs steps >= 1 and ramp_rounds >= 1 (got "
                f"steps={self.steps}, ramp_rounds={self.ramp_rounds})")
        if self.peak_delay_ms < 0:
            raise ValueError(
                f"Brownout peak_delay_ms must be >= 0 "
                f"(got {self.peak_delay_ms})")

    def _windows(self):
        step_len = max(1, self.ramp_rounds // self.steps)
        t = self.from_round
        for i in range(1, self.steps + 1):          # ramp up
            yield (t, t + step_len, self.peak_loss * i / self.steps,
                   self.peak_delay_ms * i / self.steps)
            t += step_len
        if self.hold_rounds > 0:                    # hold at the peak
            yield (t, t + self.hold_rounds, self.peak_loss,
                   self.peak_delay_ms)
            t += self.hold_rounds
        for i in range(self.steps - 1, 0, -1):      # ramp down
            yield (t, t + step_len, self.peak_loss * i / self.steps,
                   self.peak_delay_ms * i / self.steps)
            t += step_len

    def apply(self, world, n, horizon):
        for lo, hi, loss, delay in self._windows():
            world = world.with_link_fault(tuple(self.src), tuple(self.dst),
                                          loss, delay_ms=delay,
                                          from_round=lo,
                                          until_round=hi)
        return world

    def disruption(self, n, horizon):
        end = max(hi for _, hi, _, _ in self._windows())
        return (self.from_round, end)


@dataclasses.dataclass(frozen=True)
class RollingPartition:
    """``n_cycles`` of [rotated half/half split for ``phase_rounds``,
    then heal for ``phase_rounds``], starting at ``from_round`` (must be
    a multiple of ``phase_rounds`` — the rolling schedule is
    phase-quantized).  The compiled phase list is explicitly
    zero-padded past the horizon so the cycle cannot wrap back into a
    split within the run."""

    from_round: int
    phase_rounds: int
    n_cycles: int
    rotate: int = 0

    def __post_init__(self):
        if self.from_round % self.phase_rounds:
            raise ValueError(
                f"from_round ({self.from_round}) must be a multiple of "
                f"phase_rounds ({self.phase_rounds}) — partition_at "
                f"quantizes the rolling schedule by phase")

    def apply(self, world, n, horizon):
        lead = self.from_round // self.phase_rounds
        phases = [[0] * n for _ in range(lead)]
        for c in range(self.n_cycles):
            phases.append([
                1 if ((i + c * self.rotate) % n) < n // 2 else 0
                for i in range(n)
            ])
            phases.append([0] * n)
        while len(phases) * self.phase_rounds <= horizon:
            phases.append([0] * n)
        return world.with_partition_schedule(
            np.asarray(phases, dtype=np.int8), self.phase_rounds)

    def disruption(self, n, horizon):
        lead = self.from_round // self.phase_rounds
        end = (lead + 2 * self.n_cycles - 1) * self.phase_rounds
        return (self.from_round, end)


@dataclasses.dataclass(frozen=True)
class ConfigPush:
    """Owner-local config write: ``node`` sets its metadata cell ``key``
    to ``value`` at ``at_round`` (``SwimWorld.with_metadata_push`` — the
    jit analog of the reference's ``Cluster.updateMetadata``).  Requires
    the metadata plane: ``SwimParams.metadata_keys > key``
    (chaos/campaign.campaign_params enables it automatically via
    :attr:`Scenario.has_metadata`).  Not a fault: no disruption window,
    no effect on membership schedules — a scenario of pushes over a
    pristine network stays pristine."""

    node: int
    key: int
    value: int
    at_round: int

    def apply(self, world, n, horizon):
        return world.with_metadata_push(self.node, self.key, self.value,
                                        self.at_round)

    def disruption(self, n, horizon):
        return None                      # config data, not network

    def push_schedule(self):
        """[(node, key, value, round)] — the flat form the staged-rollout
        driver and the oracle replay consume."""
        return [(self.node, self.key, self.value, self.at_round)]


@dataclasses.dataclass(frozen=True)
class StagedRollout:
    """Staged config rollout: ``members`` (the rollout order) split into
    ``n_stages`` equal waves; stage s's members each push ``key`` =
    ``value`` on themselves at ``start_round + s * stage_every``.

    The op compiles the OPTIMISTIC forward schedule — every stage fires
    on time.  The convergence GATE between stages is the driver's job
    (``bench.py --rollout``): it runs segment-by-segment, polls
    ``models/metadata.divergence_probe`` at each stage boundary, and
    rolls the remaining stages forward only while each stage converges
    within its deadline (``stage_every`` must cover
    :func:`metadata_convergence_bound`, validated here so a rollout
    whose stages cannot possibly converge in time is a build-time
    error, not a mystery breach) — otherwise it REBUILDS the tail as a
    rollback push of ``rollback_value`` on the already-flipped members
    (:meth:`rollback_ops`).  A gate cannot live inside the compiled
    schedule: the world arrays are pure data, and a data-dependent push
    round would break the one-compile-per-shape campaign contract.
    """

    members: Tuple[int, ...]
    n_stages: int
    key: int
    value: int
    start_round: int
    stage_every: int
    rollback_value: int = 0

    def __post_init__(self):
        if self.n_stages < 1 or len(self.members) % self.n_stages:
            raise ValueError(
                f"n_stages {self.n_stages} must be >= 1 and divide the "
                f"member count {len(self.members)}")
        if len(set(self.members)) != len(self.members):
            raise ValueError(
                f"StagedRollout members must be distinct (got "
                f"{self.members}) — one owner cannot join two stages")
        if self.stage_every < 1:
            raise ValueError(
                f"stage_every {self.stage_every} must be >= 1")

    @property
    def stage_size(self) -> int:
        return len(self.members) // self.n_stages

    def stage_round(self, s: int) -> int:
        return self.start_round + s * self.stage_every

    def stage_members(self, s: int) -> Tuple[int, ...]:
        return self.members[s * self.stage_size:(s + 1) * self.stage_size]

    def validate_gate(self, params, n) -> None:
        """Raise unless ``stage_every`` covers the convergence bound —
        a stage that CANNOT meet its own deadline is a schedule bug,
        not a finding (the driver calls this before running)."""
        bound = metadata_convergence_bound(params, n)
        if self.stage_every < bound:
            raise ValueError(
                f"StagedRollout stage_every={self.stage_every} is below "
                f"the convergence bound {bound} for this config — no "
                f"stage could ever pass its gate "
                f"(chaos/scenarios.metadata_convergence_bound)")

    def apply(self, world, n, horizon):
        for node, key, value, at in self.push_schedule():
            world = world.with_metadata_push(node, key, value, at)
        return world

    def disruption(self, n, horizon):
        return None

    def push_schedule(self):
        return [(m, self.key, self.value, self.stage_round(s))
                for s in range(self.n_stages)
                for m in self.stage_members(s)]

    def rollback_ops(self, failed_stage: int, at_round: int
                     ) -> Tuple[ConfigPush, ...]:
        """The rollback tail after ``failed_stage`` breached its gate:
        one :class:`ConfigPush` of ``rollback_value`` at ``at_round``
        for every member of stages ``0..failed_stage`` (the flipped
        set — later stages never fired, nothing to undo)."""
        flipped = [m for s in range(failed_stage + 1)
                   for m in self.stage_members(s)]
        return tuple(ConfigPush(node=m, key=self.key,
                                value=self.rollback_value,
                                at_round=at_round) for m in flipped)


# --------------------------------------------------------------------------
# Scenario
# --------------------------------------------------------------------------


def _pad_rules(faults: "swim.LinkFaults", total: int) -> "swim.LinkFaults":
    """Pad the rule arrays to ``total`` with match-nothing rules (empty
    id ranges) so scenarios with different rule counts share one traced
    shape — the last-match-wins evaluation is unaffected."""
    r = faults.n_rules
    if r >= total:
        return faults
    pad = total - r

    def cat(a, v, dtype):
        return jnp.concatenate(
            [a, jnp.full((pad,), v, dtype=dtype)])

    return swim.LinkFaults(
        src_lo=cat(faults.src_lo, 0, jnp.int32),
        src_hi=cat(faults.src_hi, 0, jnp.int32),     # empty range
        dst_lo=cat(faults.dst_lo, 0, jnp.int32),
        dst_hi=cat(faults.dst_hi, 0, jnp.int32),
        from_round=cat(faults.from_round, 0, jnp.int32),
        until_round=cat(faults.until_round, 0, jnp.int32),
        loss=cat(faults.loss, 0.0, jnp.float32),
        delay_ms=cat(faults.delay_ms, 0.0, jnp.float32),
    )


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One declarative fault scenario (module docstring).

    ``loss_probability`` is the background symmetric wire loss the
    scenario asks the run for (a params knob, not a world schedule —
    campaign.run_scenario applies it).  ``extra_slack`` widens the
    completeness deadlines for hand-built scenarios whose network is
    harsher than the generator's tiers.  ``seed``/``severity`` are
    campaign provenance; :meth:`repro` is the one-line reconstruction
    of a generated scenario.
    """

    name: str
    n_members: int
    horizon: int
    ops: Tuple[object, ...]
    loss_probability: float = 0.0
    seed: Optional[int] = None
    severity: Optional[str] = None
    extra_slack: int = 0

    def repro(self) -> str:
        if self.seed is not None and self.severity is not None:
            return (f"chaos.generate_scenario(seed={self.seed}, "
                    f"n={self.n_members}, severity={self.severity!r})")
        return f"<hand-built scenario {self.name!r}>"

    @property
    def has_joins(self) -> bool:
        """True when any op schedules an open-world JOIN — the runner
        must enable ``SwimParams.open_world`` or the joins degrade to
        same-identity revivals (chaos/campaign.campaign_params does
        this automatically)."""
        return any(
            isinstance(op, Join)
            or (isinstance(op, ChurnStorm) and op.join_wave_size > 0)
            for op in self.ops
        )

    @property
    def has_metadata(self) -> bool:
        """True when any op pushes a metadata word — the runner must
        enable ``SwimParams.metadata_keys`` or the pushes compile to
        no-ops (chaos/campaign.campaign_params does this
        automatically, sized by :meth:`metadata_keys_needed`)."""
        return any(isinstance(op, (ConfigPush, StagedRollout))
                   for op in self.ops)

    def metadata_keys_needed(self) -> int:
        """Smallest ``SwimParams.metadata_keys`` covering every pushed
        key (0 when no op pushes — the plane stays off)."""
        keys = [op.key for op in self.ops
                if isinstance(op, (ConfigPush, StagedRollout))]
        return max(keys) + 1 if keys else 0

    def build(self, params: "swim.SwimParams",
              rule_pad: int = _RULE_PAD):
        """Compile to ``(SwimWorld, MonitorSpec)`` for ``params``."""
        n = params.n_members
        if n != self.n_members:
            raise ValueError(
                f"scenario {self.name!r} is for n_members="
                f"{self.n_members}, params has {n}")
        world = swim.SwimWorld.healthy(params)
        for op in self.ops:
            world = op.apply(world, n, self.horizon)
        r = world.faults.n_rules
        pad_to = max(rule_pad, -(-r // max(1, rule_pad)) * rule_pad)
        world = dataclasses.replace(
            world, faults=_pad_rules(world.faults, pad_to))

        disruptions = [d for d in
                       (op.disruption(n, self.horizon) for op in self.ops)
                       if d is not None]
        permanent_disruption = any(d[1] >= INT32_MAX for d in disruptions)
        disruption_end = max((d[1] for d in disruptions), default=0)
        pristine = (not disruptions
                    and params.loss_probability == 0.0
                    and self.loss_probability == 0.0
                    and params.mean_delay_ms == 0.0)

        bound = completeness_bound(params, n) + self.extra_slack
        df = np.asarray(world.down_from, dtype=np.int64)
        du = np.asarray(world.down_until, dtype=np.int64)
        la = np.asarray(world.leave_at, dtype=np.int64)
        fault = np.minimum(df, la)
        permanent = (fault < INT32_MAX) & (du >= INT32_MAX)
        checkable = permanent & (not permanent_disruption)
        deadline = np.where(
            checkable,
            np.minimum(np.maximum(fault, disruption_end) + bound,
                       INT32_MAX),
            INT32_MAX,
        )
        slot = np.asarray(world.slot_of_node)
        complete_by = np.full(params.n_subjects, INT32_MAX, dtype=np.int64)
        tracked = slot >= 0
        complete_by[slot[tracked]] = deadline[tracked]

        # JOIN-propagation deadlines (NO_RESURRECTION /
        # JOIN_COMPLETENESS): a joined identity must be globally known
        # — and no dead epoch's record survive as live — within the
        # same generous completeness bound, measured from the join (or
        # the end of the last network disruption).  No promise under a
        # permanent disruption, the COMPLETENESS rule.
        ja = np.asarray(world.join_at, dtype=np.int64)
        join_known_by = np.full(params.n_subjects, INT32_MAX,
                                dtype=np.int64)
        joins_checkable = (ja < INT32_MAX) & (not permanent_disruption)
        j_deadline = np.where(
            joins_checkable,
            np.minimum(np.maximum(ja, disruption_end) + bound, INT32_MAX),
            INT32_MAX,
        )
        join_known_by[slot[tracked]] = j_deadline[tracked]
        check_joins = bool(params.open_world and joins_checkable.any())

        # Post-heal agreement promise (POST_HEAL_DIVERGENCE): made only
        # when the SYNC anti-entropy plane is ON, the background network
        # is pristine, and every fault quiesces before its heal — the
        # preconditions under which bounded re-convergence actually
        # holds (models/sync.py "quiesced-heal precondition").
        agree_from = INT32_MAX
        if (params.sync_interval > 0
                and not permanent_disruption
                and params.loss_probability == 0.0
                and self.loss_probability == 0.0
                and params.mean_delay_ms == 0.0
                and all(self._op_quiesces(op, params, n)
                        for op in self.ops)):
            # Settling deadlines: a HEAL (disruption end, revive) needs
            # one agreement window; a fault START (crash/leave round)
            # additionally needs its own effects to mature first —
            # detection, suspicion timers, tombstone spread
            # (quiesce_bound) — before the agreement clock can run, or a
            # legitimate mid-maturation ALIVE/SUSPECT/DEAD mixture trips
            # the invariant.
            qb = quiesce_bound(params, n)
            settle = [disruption_end]
            finite_du = du[du < INT32_MAX]
            if finite_du.size:
                settle.append(int(finite_du.max()))
            for arr in (df, la):
                finite = arr[arr < INT32_MAX]
                if finite.size:
                    settle.append(int(finite.max()) + qb)
            agree_from = min(
                max(settle) + post_heal_agreement_bound(params, n)
                + self.extra_slack,
                INT32_MAX,
            )

        spec = MonitorSpec(
            complete_by=jnp.asarray(complete_by.astype(np.int32)),
            agree_from=jnp.int32(agree_from),
            check_agreement=agree_from < INT32_MAX,
            check_false_suspicion=pristine,
            join_known_by=jnp.asarray(join_known_by.astype(np.int32)),
            check_joins=check_joins,
        )
        return world, spec

    @staticmethod
    def _op_quiesces(op, params: "swim.SwimParams", n: int) -> bool:
        """Does this op's disturbance go cold before its own heal (the
        agreement-promise precondition)?  Process faults must be
        permanent or down for >= quiesce_bound; partitions must hold
        each phase >= quiesce_bound; probabilistic network ops (loss,
        flaps, brownouts) never promise — their false suspicions mature
        on their own clocks."""
        qb = quiesce_bound(params, n)
        if isinstance(op, (Crash, CrashBurst)):
            return (op.until_round >= INT32_MAX
                    or op.until_round - op.at_round >= qb)
        if isinstance(op, Leave):
            return True                  # announces its own death
        if isinstance(op, Join):
            return False                 # identity rebirth: join codes own it
        if isinstance(op, ChurnStorm):
            if op.join_wave_size:
                # Arrival storms rebirth slots mid-run; the live-consensus
                # agreement clock has no settled meaning across identity
                # epochs — the join codes own that contract instead.
                return False
            return op.down_rounds == 0 or op.down_rounds >= qb
        if isinstance(op, RollingPartition):
            return op.phase_rounds >= qb
        if isinstance(op, (ConfigPush, StagedRollout)):
            return True                  # config data: no fault to cool
        return False


def asymmetric_degraded_range(n: int) -> int:
    """Size of :func:`asymmetric_degradation`'s degraded observer range
    (ids ``[0, q)``) — ONE place, consumed by the scenario builder AND
    ``bench.py --lifeguard`` (which crashes exactly this rack for its
    detection-parity probe; a drifted copy would silently crash healthy
    members and corrupt the A/B)."""
    return max(2, n // 8)


def asymmetric_degradation(seed: int, n: int = 32,
                           peak_loss: float = 0.3,
                           peak_delay_ms: float = 300.0,
                           hold_rounds: int = 200,
                           params: Optional["swim.SwimParams"] = None
                           ) -> Scenario:
    """Seeded composite for the Lifeguard headline experiment
    (bench.py --lifeguard): observer-side asymmetric degradation.

    A small minority of the id range (``max(2, n // 8)`` members —
    Lifeguard's operating regime: degraded members are rare, a cluster
    losing a quarter of its probe capacity cannot keep detection
    latency flat under ANY adaptivity) are the DEGRADED OBSERVERS: a
    :class:`Brownout` ramps loss AND mean link delay on their INBOUND
    links (src = the healthy majority, dst = the degraded range) up to
    the peaks and holds — their probes of perfectly healthy peers drop
    or time out on the ack hop, which is exactly the observer-local
    unreliability Lifeguard's LHM detects (the outbound direction
    stays clean, so their false SUSPECT verdicts still disseminate at
    full rate — the worst case for cluster-wide false positives).  The
    delay component is the regime the LHA *timeout* scaling repairs
    outright (a stretched budget lets the slow acks land) while true
    crash detection is untouched (a crashed target never acks, at any
    budget).  A seeded :class:`FlappingLink` into the same range rides
    along for non-stationary flap noise.  The rest of the network is
    pristine.

    Pure in ``(seed, n)`` like :func:`generate_scenario` — one-line
    repro: ``chaos.asymmetric_degradation(seed=S, n=N)``.
    """
    if n < 16:
        raise ValueError(
            f"asymmetric_degradation needs n >= 16 (got {n}) — the "
            f"degraded range must stay a strict minority")
    if params is None:
        from scalecube_cluster_tpu.chaos.campaign import campaign_config
        params = swim.SwimParams.from_config(campaign_config(), n_members=n)
    rng = np.random.default_rng(np.random.SeedSequence([seed, 0x11F6]))
    q = asymmetric_degraded_range(n)            # degraded observer range
    ops = (
        Brownout(src=(q, n), dst=(0, q), peak_loss=float(peak_loss),
                 peak_delay_ms=float(peak_delay_ms),
                 from_round=0, ramp_rounds=12,
                 hold_rounds=int(hold_rounds), steps=3),
        FlappingLink(src=int(rng.integers(q, n)),
                     dst=int(rng.integers(0, q)),
                     from_round=int(rng.integers(0, 9)),
                     n_cycles=4, down_rounds=6, up_rounds=10),
    )
    ends = [op.disruption(n, 10 ** 9)[1] for op in ops]
    horizon = _quantize_horizon(
        max(ends) + completeness_bound(params, n) // 2 + 24)
    return Scenario(name=f"asym-deg-{seed}-n{n}", n_members=n,
                    horizon=horizon, ops=ops, seed=seed)


def alarm_drill_scenario(seed: int, n: int = 32,
                         pulse_loss: float = 0.6,
                         onset_round: int = 128,
                         pulse_rounds: int = 128,
                         cool_rounds: int = 128) -> Scenario:
    """Seeded square-pulse fault for the live-alarm drill
    (bench.py --alarms): a sharp-edged inbound :class:`LinkLoss` window
    on the drill range's links.

    During ``[onset_round, onset_round + pulse_rounds)`` messages from
    the healthy majority INTO ids ``[0, q)`` (``q =``
    :func:`asymmetric_degraded_range` — the lifeguard drill's rack)
    drop at ``pulse_loss``; outside the pulse the network is pristine.
    A square pulse on purpose, where :func:`asymmetric_degradation`
    ramps: the drill measures DETECTION LAG against a known onset
    round, so the fault edge must be one round wide — a ramp would
    smear the very quantity under test.  Probes of the range fail on
    the ping hop, false suspicions onset at the pulse edge and stop at
    the heal, which is exactly the breach/resolve timeline the alarm's
    pending→firing→resolved machine must track.

    The horizon leaves ``cool_rounds`` after the heal so the resolve
    hysteresis has clear windows to consume.  Pure in its arguments
    (the pulse is deterministic; ``seed`` seeds the RUN key and names
    the repro): ``chaos.alarm_drill_scenario(seed=S, n=N)``.
    """
    if n < 16:
        raise ValueError(
            f"alarm_drill_scenario needs n >= 16 (got {n}) — the "
            f"pulsed range must stay a strict minority")
    if pulse_rounds < 1 or cool_rounds < 1:
        raise ValueError(
            f"alarm_drill_scenario needs pulse_rounds >= 1 and "
            f"cool_rounds >= 1 (got {pulse_rounds}, {cool_rounds}) — "
            f"no pulse means no breach, no cooldown means no resolve")
    q = asymmetric_degraded_range(n)
    ops = (
        LinkLoss(src=(q, n), dst=(0, q), loss=float(pulse_loss),
                 from_round=int(onset_round),
                 until_round=int(onset_round + pulse_rounds)),
    )
    return Scenario(name=f"alarm-drill-{seed}-n{n}", n_members=n,
                    horizon=int(onset_round + pulse_rounds + cool_rounds),
                    ops=ops, seed=seed)


def blame_drill_scenario(seed: int, n: int = 32,
                         victim: int = 3, observer: int = 11,
                         onset_round: int = 32,
                         pulse_rounds: int = 96,
                         cool_rounds: int = 96) -> Scenario:
    """Seeded single-fault drill for the provenance blame engine
    (bench.py --blame): ONE asymmetric faulty link, one victim.

    During ``[onset_round, onset_round + pulse_rounds)`` every message
    from ``victim`` TO ``observer`` drops (``loss=1.0`` on that one
    directed link) while every other link — including the reverse
    direction — stays pristine.  The observer's direct probes of the
    victim reach it fine but the acks never come back, so the observer
    (and ONLY the observer, first-hand) times the victim out and
    starts the false suspicion; everyone else learns of it second-hand
    via piggyback gossip, and the victim — alive the whole time —
    refutes with an incarnation bump that spreads through third
    parties.  That is exactly the asymmetry the blame report must see
    through: ``origin_observer`` must name the observer even though
    most of the cluster heard the rumor from a gossip carrier.

    Run it with ``ping_req_members=0`` (the bench does) so the
    first-hand sighting is unambiguously ``fd_direct`` — a ping-req
    proxy probing on the observer's behalf would get an ack (the
    victim→proxy link is clean) and mask the fault.  The pulse heals
    after ``pulse_rounds`` and the horizon leaves ``cool_rounds`` for
    the refutation to settle.  Pure in its arguments (the fault is
    deterministic; ``seed`` seeds the RUN key and names the repro):
    ``chaos.blame_drill_scenario(seed=S, n=N)``.
    """
    if n < 16:
        raise ValueError(
            f"blame_drill_scenario needs n >= 16 (got {n}) — the "
            f"rumor needs a crowd of second-hand observers")
    if not (0 <= victim < n and 0 <= observer < n) or victim == observer:
        raise ValueError(
            f"blame_drill_scenario needs distinct victim/observer ids "
            f"in [0, {n}) (got {victim}, {observer})")
    if pulse_rounds < 1 or cool_rounds < 1:
        raise ValueError(
            f"blame_drill_scenario needs pulse_rounds >= 1 and "
            f"cool_rounds >= 1 (got {pulse_rounds}, {cool_rounds}) — "
            f"no pulse means no suspicion, no cooldown means no "
            f"refutation window")
    ops = (
        LinkLoss(src=int(victim), dst=int(observer), loss=1.0,
                 from_round=int(onset_round),
                 until_round=int(onset_round + pulse_rounds)),
    )
    return Scenario(name=f"blame-drill-{seed}-n{n}", n_members=n,
                    horizon=int(onset_round + pulse_rounds + cool_rounds),
                    ops=ops, seed=seed)


def churn_growth_scenario(seed: int, n: int = 32, waves: int = 3,
                          wave_size: int = 2, join_wave_size: int = 3,
                          join_lag: Optional[int] = None,
                          params: Optional["swim.SwimParams"] = None
                          ) -> Scenario:
    """The canonical NET-POSITIVE arrival storm — the ``bench.py
    --churn`` A/B workload and the open-world monitor tests run this
    one schedule, so the growth arithmetic cannot drift between them.
    (The oracle mid-run-join cross-validation runs a separate QUIESCED
    scare-free schedule instead — ``campaign.cross_validate_churn``
    rejects network ops, and mid-suspicion joins make the two layers'
    REMOVED key sets legitimately diverge, so this adversarial storm
    is validated by the invariant monitor, not by oracle replay.)

    ``waves`` crash waves of ``wave_size`` kill members permanently
    while each wave admits ``join_wave_size`` NEW identities
    (``join_wave_size > wave_size`` ⇒ net growth of
    ``waves * (join_wave_size - wave_size)`` members, drawn from a
    pre-dead arrivals pool of exactly that size — every free slot is
    consumed and every crashed slot recycled).  ``join_lag`` defaults
    to 10 rounds: joins land MID-SUSPICION of the previous occupant —
    observers still hold its ALIVE/SUSPECT records and its tombstones
    mature (hot) only after the new member is already in, the
    adversarial recycling window where naive slot reuse demonstrably
    shadows, kills and resurrects identities while the epoch guard
    (plus its dead_suppress_rounds interplay) must hold.

    Each wave victim additionally suffers a pre-death SCARE — a brief
    inbound blockade that gets it falsely suspected, healed, and
    self-refuted — so the occupants die at incarnation >= 1, the
    operationally normal state of a long-lived member.  This is what
    makes naive reuse's resurrection OBSERVABLE: the dead identity's
    ALIVE@inc>=1 records outrank the new member's ALIVE@0 on an
    epoch-blind wire (chaos/monitor.NO_RESURRECTION's incarnation
    forensics), while the epoch guard drops them outright.

    Pure in ``(seed, n)``: one-line repro
    ``chaos.churn_growth_scenario(seed=S, n=N)``.
    """
    if n < 16:
        raise ValueError(
            f"churn_growth_scenario needs n >= 16 (got {n}) — the storm "
            f"pools must stay a minority of the cluster")
    if join_wave_size <= wave_size:
        raise ValueError(
            f"net-positive growth needs join_wave_size ({join_wave_size})"
            f" > wave_size ({wave_size})")
    if params is None:
        from scalecube_cluster_tpu.chaos.campaign import campaign_config
        params = swim.SwimParams.from_config(campaign_config(), n_members=n)
    n_pool = waves * wave_size
    n_arrivals = waves * (join_wave_size - wave_size)
    if n_pool + n_arrivals > n - 2:
        raise ValueError(
            f"storm pools ({n_pool} crash + {n_arrivals} arrival slots) "
            f"leave fewer than 2 stable members at n={n}")
    if join_lag is None:
        join_lag = 10
    rng = np.random.default_rng(np.random.SeedSequence([seed, 0x10E6]))
    pool = [int(x) for x in rng.permutation(n)]
    nodes = tuple(pool[:n_pool])
    arrivals = tuple(pool[n_pool:n_pool + n_arrivals])
    wave_every = max(int(join_lag) + 2, 16)
    # Scare geometry: blockade ends >= ~suspicion_rounds/2 before the
    # crash so the refutation lands and goes cold pre-death, and starts
    # late enough that the suspicion cannot mature DEAD mid-scare.
    scare_len = 6
    scare_gap = min(params.suspicion_rounds - scare_len - 2, 14)
    scare_lead = scare_len + max(scare_gap, 6)
    storm = ChurnStorm(
        nodes=nodes, wave_size=wave_size,
        start_round=scare_lead + int(rng.integers(4, 11)),
        wave_every=wave_every,
        join_wave_size=join_wave_size, join_lag=int(join_lag),
        arrivals=arrivals,
    )
    scares = []
    for w in range(storm.n_waves):
        at = storm.start_round + w * wave_every
        for v in nodes[w * wave_size:(w + 1) * wave_size]:
            scares.append(LinkLoss(
                src=(0, n), dst=v, loss=1.0,
                from_round=at - scare_lead,
                until_round=at - scare_lead + scare_len,
            ))
    last_join = (storm.start_round + (storm.n_waves - 1) * wave_every
                 + storm.join_lag)
    horizon = _quantize_horizon(
        last_join + completeness_bound(params, n) + 24)
    return Scenario(name=f"churn-growth-{seed}-n{n}", n_members=n,
                    horizon=horizon, ops=(*scares, storm), seed=seed)


# --------------------------------------------------------------------------
# Seeded campaign generation
# --------------------------------------------------------------------------


def _quantize_horizon(rounds: int) -> int:
    return -(-rounds // _HORIZON_QUANTUM) * _HORIZON_QUANTUM


def generate_scenario(seed: int, n: int = 32, severity: str = "moderate",
                      params: Optional["swim.SwimParams"] = None
                      ) -> Scenario:
    """One scenario, a PURE function of (seed, n, severity) — the
    campaign repro unit.  ``params`` only shapes the completeness/
    horizon arithmetic (defaults to the campaign timing preset at n;
    chaos/campaign.campaign_config)."""
    if severity not in SEVERITIES:
        raise ValueError(f"unknown severity {severity!r} "
                         f"(choose from {SEVERITIES})")
    if n < 16:
        raise ValueError(f"campaign scenarios need n >= 16 (got {n})")
    if params is None:
        from scalecube_cluster_tpu.chaos.campaign import campaign_config
        params = swim.SwimParams.from_config(campaign_config(), n_members=n)
    rng = np.random.default_rng(
        np.random.SeedSequence([seed, SEVERITIES.index(severity)]))
    pool = [int(x) for x in rng.permutation(n)]
    bound = completeness_bound(params, n)
    revive_down = int(2 * params.suspicion_rounds + 24)

    def take(k):
        out, pool[:] = pool[:k], pool[k:]
        return out

    ops, kinds = [], []

    def add(kind, op):
        kinds.append(kind)
        ops.append(op)

    def op_crash():
        add("crash", Crash(take(1)[0], at_round=int(rng.integers(0, 11))))

    def op_crash_revive():
        at = int(rng.integers(0, 9))
        add("crash_revive",
            Crash(take(1)[0], at_round=at, until_round=at + revive_down))

    def op_leave():
        add("leave", Leave(take(1)[0], at_round=int(rng.integers(2, 13))))

    def op_flap():
        s, d = take(2)
        add("flap", FlappingLink(s, d, from_round=int(rng.integers(0, 9)),
                                 n_cycles=3, down_rounds=4, up_rounds=6))

    def op_burst(permanent=True):
        sz = int(rng.integers(2, 4))
        at = int(rng.integers(2, 11))
        until = INT32_MAX if permanent else at + revive_down
        add("burst", CrashBurst(tuple(take(sz)), at_round=at,
                                until_round=until))

    def op_churn(permanent):
        nodes = tuple(take(4))
        add("churn", ChurnStorm(nodes, wave_size=2,
                                start_round=int(rng.integers(2, 7)),
                                wave_every=int(rng.integers(6, 13)),
                                down_rounds=0 if permanent else revive_down))

    def op_churn_arrivals():
        # Net-positive arrival storm: 2 waves kill 2 + 2 and admit
        # 3 + 3 new identities (one-slot growth per wave from a
        # pre-dead arrivals pool) — the open-world severity rung.
        # Joins land as the previous occupants' tombstones mature
        # (the adversarial recycling window, churn_growth_scenario).
        nodes = tuple(take(4))
        arrivals = tuple(take(2))
        lag = int(params.suspicion_rounds) + int(rng.integers(4, 13))
        add("churn_arrivals", ChurnStorm(
            nodes, wave_size=2,
            start_round=int(rng.integers(2, 7)),
            wave_every=lag + int(rng.integers(2, 7)),
            join_wave_size=3, join_lag=lag, arrivals=arrivals))

    def op_brownout():
        half = n // 2
        add("brownout", Brownout(
            src=(0, half), dst=(half, n),
            peak_loss=float(rng.choice([0.3, 0.5])),
            from_round=int(rng.integers(0, 9)),
            ramp_rounds=12, hold_rounds=10))

    loss = 0.0
    if severity == "mild":
        rng.choice([op_crash, op_crash_revive, op_leave, op_flap])()
    elif severity == "moderate":
        loss = float(rng.choice([0.0, 0.02, 0.05]))
        menu = [lambda: op_burst(bool(rng.integers(0, 2))),
                lambda: op_churn(bool(rng.integers(0, 2))),
                op_flap, op_brownout, op_leave]
        for f in rng.choice(len(menu), size=2, replace=False):
            menu[int(f)]()
    else:                                           # severe
        loss = float(rng.choice([0.05, 0.1]))
        add("partition", RollingPartition(
            from_round=0, phase_rounds=16, n_cycles=2,
            rotate=int(rng.integers(0, n))))
        op_churn(permanent=bool(rng.integers(0, 2)))
        (op_brownout if rng.integers(0, 2) else op_flap)()

    # Open-world rung (PR 10): moderate/severe tiers additionally emit
    # a net-positive arrival storm for half the seeds.  The draw TRAILS
    # every existing one, so the ops a pre-open-world seed generated are
    # unchanged — the tier grows, it does not reshuffle (the campaign
    # repro contract: generate_scenario stays pure in (seed, n,
    # severity), and historical seeds keep their historical faults).
    if severity != "mild" and n >= 24 and rng.integers(0, 2):
        op_churn_arrivals()

    # Metadata rung (PR 19): every tier additionally pushes one config
    # word for half the seeds — a live owner (drawn from the untouched
    # remainder of the pool) flips a key mid-faults, so the campaign
    # invariant monitor exercises the KV plane under the tier's own
    # chaos.  The draw TRAILS every existing one including the arrival
    # coin above (the PR-10 rule: historical seeds keep their historical
    # ops — the tier grows, it does not reshuffle).
    if rng.integers(0, 2):
        add("config_push", ConfigPush(
            node=take(1)[0], key=0,
            value=int(rng.integers(1, metadata.MD_VALUE_MAX + 1)),
            at_round=int(rng.integers(4, 17))))

    # Horizon: every fault/disruption resolved, plus the completeness
    # bound and a margin — quantized so campaigns share compilations.
    ends = [0]
    for op in ops:
        d = op.disruption(n, 10 ** 9)
        if d is not None and d[1] < INT32_MAX:
            ends.append(d[1])
        for attr in ("at_round", "until_round", "start_round"):
            v = getattr(op, attr, None)
            if v is not None and v < INT32_MAX:
                ends.append(int(v))
        if isinstance(op, ChurnStorm):
            ends.append(op.start_round
                        + op.n_waves * op.wave_every + op.down_rounds
                        + op.join_lag)
    horizon = _quantize_horizon(max(ends) + bound + 24)
    name = f"{severity}-{seed}-" + "+".join(kinds)
    return Scenario(name=name, n_members=n, horizon=horizon,
                    ops=tuple(ops), loss_probability=loss, seed=seed,
                    severity=severity)


def generate_campaign(seed: int, n_scenarios: int, n: int = 32,
                      severities: Sequence[str] = SEVERITIES) -> list:
    """``n_scenarios`` scenarios cycling through ``severities``;
    scenario i is ``generate_scenario(seed + i, n, severities[i %
    len(severities)])`` — every member is its own one-line repro."""
    return [
        generate_scenario(seed + i, n=n,
                          severity=severities[i % len(severities)])
        for i in range(n_scenarios)
    ]


def generate_fuzz_campaign(seed: int, seeds_per_tier: int, n: int = 32,
                           severities: Sequence[str] = SEVERITIES
                           ) -> list:
    """The mega-campaign form of :func:`generate_campaign`:
    ``seeds_per_tier`` scenarios PER severity tier, tier-cycled so
    scenario i's generation seed stays ``seed + i`` — the run-seed
    alignment that keeps every verdict row's repro line exact when a
    campaign runner assigns run seed ``seed + i`` by position
    (chaos/campaign.run_campaign / run_campaign_vmapped).

    By construction ``generate_fuzz_campaign(seed, k)`` ==
    ``generate_campaign(seed, k * len(severities))``; the name states
    the scaling contract: thousands of seeds per tier, quantized
    horizons and padded rule widths collapsing them into a handful of
    compile buckets, one vmapped device program per bucket
    (chaos/campaign.build_buckets)."""
    return generate_campaign(seed, seeds_per_tier * len(severities),
                             n=n, severities=severities)
