"""Campaign runner: monitored scenario sweeps, oracle cross-validation,
JSONL verdict manifests.

``run_scenario`` compiles one :class:`~.scenarios.Scenario` against the
campaign timing preset, runs it through the in-jit invariant monitor
(:func:`~.monitor.run_monitored`) and returns a
:class:`ScenarioVerdict`.  ``run_campaign`` sweeps a scenario list and
writes one JSONL manifest through the existing telemetry pipeline
(telemetry/sink.py): a ``manifest`` header, one ``chaos_scenario`` row
per scenario (green flag, per-code violation counts, first-violation
evidence lanes, counter digests, the one-line repro) and a closing
``chaos_verdict`` summary — greppable, appendable, round-trippable by
``sink.read_records``.

``cross_validate`` replays a crash/leave scenario on the event-driven
oracle under the SAME fault schedule (crash = the full link blockade of
tests/test_telemetry_trace.py — the oracle transport has no restart;
leave = ``Cluster.shutdown``) and diffs the timing-free event key sets
of the model's on-device trace against the oracle's listener stream,
restricted to continuously-live observers — the small-N ground-truth
check that the monitor's "green" and the oracle's behavior agree.
Scenarios quiesce by construction (permanent crashes, or revives long
after removal completes), which is what makes the key sets
deterministic and diffable (telemetry/events.py timing caveat).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

from scalecube_cluster_tpu.chaos import monitor as cmonitor
from scalecube_cluster_tpu.chaos import scenarios as cscenarios
from scalecube_cluster_tpu.config import ClusterConfig
from scalecube_cluster_tpu.models import metadata, swim

INT32_MAX = cscenarios.INT32_MAX


def campaign_config() -> ClusterConfig:
    """The campaign timing preset: the sped-up two-layer config of
    tests/test_cross_validation.py (gossip 100 ms = 1 round, suspicion
    resolves in tens of rounds) so scenarios quiesce fast on any
    backend."""
    return ClusterConfig.default_local().replace(
        gossip_interval=100,
        ping_interval=200,
        ping_timeout=100,
        sync_interval=1_000,
        suspicion_mult=3,
    )


def campaign_params(scenario: "cscenarios.Scenario",
                    delivery: str = "shift",
                    **overrides) -> "swim.SwimParams":
    """SwimParams for one scenario: full view (every member a tracked
    subject — chaos verdicts are about the whole membership matrix),
    the scenario's background wire loss baked in, the open-world
    plane enabled automatically when the scenario schedules JOINs
    (without it the joins would degrade to same-identity revivals —
    Scenario.has_joins), and the metadata KV plane enabled — sized by
    Scenario.metadata_keys_needed — when any op pushes a config word
    (without it the pushes compile to no-ops).  Explicit overrides
    win."""
    kwargs = dict(loss_probability=scenario.loss_probability,
                  delivery=delivery)
    if scenario.has_joins:
        kwargs["open_world"] = True
    if scenario.has_metadata:
        kwargs["metadata_keys"] = scenario.metadata_keys_needed()
    kwargs.update(overrides)
    return swim.SwimParams.from_config(
        campaign_config(), n_members=scenario.n_members, **kwargs)


@dataclasses.dataclass
class ScenarioVerdict:
    """One scenario's outcome: the monitor verdict + run provenance."""

    scenario: "cscenarios.Scenario"
    green: bool
    verdict: dict                  # chaos.monitor.verdict() digest
    seed: int
    delivery: str
    counters: dict                 # summed per-round protocol counters
    cross_validation: Optional[dict] = None

    def repro(self) -> str:
        """The FULL one-line repro: scenario reconstruction + the run's
        PRNG seed (violations under loss/partitions depend on the
        stream, so the scenario line alone does not reproduce)."""
        return (f"chaos.run_scenario({self.scenario.repro()}, "
                f"seed={self.seed}, delivery={self.delivery!r})")

    def to_json(self) -> dict:
        return {
            "name": self.scenario.name,
            "severity": self.scenario.severity,
            "n_members": self.scenario.n_members,
            "horizon": self.scenario.horizon,
            "loss_probability": self.scenario.loss_probability,
            "ops": [f"{type(op).__name__}{dataclasses.asdict(op)}"
                    for op in self.scenario.ops],
            "repro": self.repro(),
            "seed": self.seed,
            "delivery": self.delivery,
            "green": self.green,
            "verdict": self.verdict,
            "counters": self.counters,
            "cross_validation": self.cross_validation,
        }


@dataclasses.dataclass
class CampaignResult:
    verdicts: List[ScenarioVerdict]
    manifest_path: Optional[str]
    # Vmapped campaigns record their compile-shape buckets (size,
    # horizon, n) — the no-silent-caps accounting of run_campaign_vmapped;
    # None for the sequential runner.
    buckets: Optional[List[dict]] = None

    @property
    def green(self) -> bool:
        return all(v.green for v in self.verdicts)

    def summary(self) -> dict:
        by_code: dict = {}
        for v in self.verdicts:
            for code, d in v.verdict["codes"].items():
                by_code[code] = by_code.get(code, 0) + d["violations"]
        return {
            "scenarios": len(self.verdicts),
            "green_scenarios": sum(v.green for v in self.verdicts),
            "green": self.green,
            "violations_by_code": by_code,
            "failing_repros": [v.repro() for v in self.verdicts
                               if not v.green],
        }


_COUNTER_KEYS = ("false_suspicion_onsets", "false_positives",
                 "refutations", "messages_gossip", "messages_ping_sent")


def run_scenario(scenario: "cscenarios.Scenario", seed: int = 0,
                 delivery: str = "shift",
                 capacity: int = cmonitor.DEFAULT_CAPACITY,
                 knobs=None, **param_overrides) -> ScenarioVerdict:
    """Compile + run one scenario through the monitored scan.

    Never raises on a violated invariant — the run completes and the
    red verdict carries the evidence (graceful degradation); only a
    malformed scenario (DSL validation) raises, at build time.

    ``knobs``: optional dynamic-knob override for the run — a
    ``swim.Knobs`` or a callable ``params -> Knobs`` (the weakened-build
    hook, :func:`weakened_knobs`); None runs the params' own schedule.
    """
    import jax

    params = campaign_params(scenario, delivery=delivery,
                             **param_overrides)
    world, spec = scenario.build(params)
    _, mon, metrics = cmonitor.run_monitored(
        jax.random.key(seed), params, world, spec, scenario.horizon,
        capacity=capacity,
        knobs=knobs(params) if callable(knobs) else knobs,
    )
    v = cmonitor.verdict(mon)
    counters = {
        k: int(np.asarray(metrics[k]).sum())
        for k in _COUNTER_KEYS if k in metrics
    }
    return ScenarioVerdict(scenario=scenario, green=v["green"],
                           verdict=v, seed=seed, delivery=delivery,
                           counters=counters)


def run_campaign(scenarios: Sequence["cscenarios.Scenario"],
                 seed: int = 0, delivery: str = "shift",
                 sink=None, log=None,
                 cross_validate_small_n: bool = False) -> CampaignResult:
    """Sweep ``scenarios`` through :func:`run_scenario`; write one
    JSONL manifest when ``sink`` (a telemetry.sink.TelemetrySink) is
    given.  Scenario i runs with PRNG seed ``seed + i`` — when the
    scenario list comes from ``generate_campaign`` with the SAME base
    seed, a scenario's run seed equals its scenario seed, which is
    what makes each verdict row's ``repro`` line exact.
    ``cross_validate_small_n`` additionally replays every
    oracle-expressible scenario (crash/leave ops only) on the oracle
    and attaches the event-diff to its verdict row."""
    verdicts = []
    if sink is not None:
        sink.write_manifest(
            params=campaign_config(),       # digest groups same-preset runs
            workload={"kind": "chaos_campaign",
                      "scenarios": len(scenarios), "seed": seed,
                      "delivery": delivery},
        )
    for i, scen in enumerate(scenarios):
        v = run_scenario(scen, seed=seed + i, delivery=delivery)
        if cross_validate_small_n:
            v.cross_validation = cross_validate(scen, seed=seed + i,
                                                delivery=delivery)
        verdicts.append(v)
        if log is not None:
            log.info("chaos scenario %s: %s", scen.name,
                     "green" if v.green else
                     f"RED {v.verdict['codes']}")
        if sink is not None:
            sink.write_record("chaos_scenario", v.to_json())
    result = CampaignResult(verdicts=verdicts,
                            manifest_path=getattr(sink, "path", None))
    if sink is not None:
        sink.write_record("chaos_verdict", result.summary())
    return result


# --------------------------------------------------------------------------
# The vmapped mega-campaign: bucket by compiled shape, fuzz per bucket
# --------------------------------------------------------------------------


def _bucket_key(params: "swim.SwimParams", horizon: int, world, spec):
    """The compiled shape signature one vmapped batch must share: the
    (hashable, static) params, the scan length, and the full treedef +
    leaf shapes/dtypes of the built (world, spec) pytrees.  Everything
    that picks an XLA program for the monitored scan is in here — rule
    pad widths and partition-schedule lengths via the world leaf
    shapes, the monitor's static check flags via the spec treedef."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((world, spec))
    shapes = tuple((tuple(x.shape), str(x.dtype)) for x in leaves)
    return (params, int(horizon), treedef, shapes)


@dataclasses.dataclass
class ScenarioBucket:
    """One compile-shape bucket of a vmapped campaign: scenarios whose
    :func:`_bucket_key` signatures are identical, their built
    worlds/specs/keys/knobs stacked along a leading batch axis so ONE
    device program (chaos/monitor.run_monitored_batch) fuzzes them all.
    ``members`` keeps the unstacked (world, spec) pairs for the
    sequential arm and per-row replays."""

    indices: List[int]
    scenarios: List["cscenarios.Scenario"]
    params: "swim.SwimParams"
    horizon: int
    worlds: object                  # stacked SwimWorld pytree [B, ...]
    specs: object                   # stacked MonitorSpec pytree [B, ...]
    keys: object                    # [B] PRNG keys (seed + scenario index)
    knobs: object                   # stacked swim.Knobs pytree [B]
    members: List[tuple]            # unstacked [(world, spec)] per row

    @property
    def size(self) -> int:
        return len(self.indices)


def build_buckets(scenarios: Sequence["cscenarios.Scenario"],
                  seed: int = 0, delivery: str = "shift",
                  knobs_fn=None, log=None,
                  **param_overrides) -> List[ScenarioBucket]:
    """Bucket ``scenarios`` by compiled shape signature and stack each
    bucket's built pytrees along a leading batch axis — the vmapped
    mega-campaign input.  Row i keeps the sequential path's PRNG seed
    ``seed + i`` (i = the scenario's position in the input list), so a
    bucketed run's verdicts are bit-comparable to ``run_campaign`` on
    the same list.

    NEVER drops a scenario: every index lands in exactly ONE bucket —
    singletons included (a batch of one still runs) — and bucket sizes
    are logged per the no-silent-caps rule; ``run_campaign_vmapped``
    additionally writes them into the manifest.

    ``knobs_fn(scenario, params) -> swim.Knobs`` overrides the per-row
    dynamic knobs (default ``Knobs.from_params``) — the deliberately-
    weakened coverage arm's hook (:func:`weakened_knobs`); knob changes
    are traced data, so they never split a bucket.
    """
    import jax
    import jax.numpy as jnp

    groups: dict = {}
    order: list = []
    for i, scen in enumerate(scenarios):
        params = campaign_params(scen, delivery=delivery,
                                 **param_overrides)
        world, spec = scen.build(params)
        key = _bucket_key(params, scen.horizon, world, spec)
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append((i, scen, params, world, spec))

    def stack(*xs):
        return jnp.stack(xs)

    buckets = []
    for key in order:
        members = groups[key]
        params = members[0][2]
        buckets.append(ScenarioBucket(
            indices=[m[0] for m in members],
            scenarios=[m[1] for m in members],
            params=params,
            horizon=members[0][1].horizon,
            worlds=jax.tree_util.tree_map(stack, *[m[3] for m in members]),
            specs=jax.tree_util.tree_map(stack, *[m[4] for m in members]),
            keys=jnp.stack([jax.random.key(seed + m[0]) for m in members]),
            knobs=jax.tree_util.tree_map(stack, *[
                (knobs_fn(m[1], params) if knobs_fn is not None
                 else swim.Knobs.from_params(params)) for m in members]),
            members=[(m[3], m[4]) for m in members],
        ))
    if log is not None:
        for b in buckets:
            log.info(
                "chaos bucket: %d scenario(s) @ n=%d horizon=%d "
                "(first: %s)", b.size, b.params.n_members, b.horizon,
                b.scenarios[0].name)
    return buckets


def run_bucket(bucket: ScenarioBucket,
               capacity: int = cmonitor.DEFAULT_CAPACITY, knobs=None):
    """One vmapped device call for one bucket.  Returns
    ``(monitors, metrics)``, both with a leading batch axis; ``knobs``
    overrides the bucket's stacked knobs (same pytree shapes -> the
    weakened rerun reuses the compiled program)."""
    _, mon, metrics = cmonitor.run_monitored_batch(
        bucket.keys, bucket.params, bucket.worlds, bucket.specs,
        bucket.horizon, capacity=capacity,
        knobs=bucket.knobs if knobs is None else knobs)
    return mon, metrics


def run_campaign_vmapped(scenarios: Sequence["cscenarios.Scenario"],
                         seed: int = 0, delivery: str = "shift",
                         capacity: int = cmonitor.DEFAULT_CAPACITY,
                         sink=None, log=None, knobs_fn=None,
                         buckets: Optional[List[ScenarioBucket]] = None
                         ) -> CampaignResult:
    """The vmapped mega-campaign: ``scenarios`` bucketed by compiled
    shape signature (:func:`build_buckets`), each bucket fuzzed by ONE
    device program — a ``jax.vmap`` of the monitored scan over the
    scenario batch axis — with per-scenario verdict extraction.  Row
    i's verdict is exactly what sequential ``run_scenario(scenarios[i],
    seed=seed + i)`` would produce (parity pinned tier-1 by
    tests/test_chaos_fuzz.py).

    The manifest mirrors ``run_campaign`` (manifest header,
    ``chaos_scenario`` rows in scenario order, closing ``chaos_verdict``)
    plus one ``chaos_bucket`` row per bucket — bucket sizes are never
    silent.  ``buckets`` accepts prebuilt buckets (bench.py --fuzz
    builds once and times several sweeps over them).
    """
    if buckets is None:
        buckets = build_buckets(scenarios, seed=seed, delivery=delivery,
                                knobs_fn=knobs_fn, log=log)
    if sink is not None:
        sink.write_manifest(
            params=campaign_config(),
            workload={"kind": "chaos_campaign_vmapped",
                      "scenarios": len(scenarios), "seed": seed,
                      "delivery": delivery,
                      "bucket_sizes": [b.size for b in buckets]},
        )
    verdicts: List[Optional[ScenarioVerdict]] = [None] * len(scenarios)
    for b in buckets:
        mon_b, metrics_b = run_bucket(b, capacity=capacity)
        rows = cmonitor.unstack_monitor(mon_b)
        # One device->host transfer per counter key, not per (row, key).
        host_counters = {k: np.asarray(metrics_b[k])
                         for k in _COUNTER_KEYS if k in metrics_b}
        for j, (i, scen, mon) in enumerate(zip(b.indices, b.scenarios,
                                               rows)):
            v = cmonitor.verdict(mon)
            counters = {k: int(c[j].sum())
                        for k, c in host_counters.items()}
            verdicts[i] = ScenarioVerdict(
                scenario=scen, green=v["green"], verdict=v,
                seed=seed + i, delivery=delivery, counters=counters)
        if sink is not None:
            sink.write_record("chaos_bucket", {
                "scenarios": b.size,
                "n_members": b.params.n_members,
                "horizon": b.horizon,
                "green_scenarios": sum(
                    1 for i in b.indices if verdicts[i].green),
            })
        if log is not None:
            log.info("chaos bucket (%d scenarios, horizon %d): %d green",
                     b.size, b.horizon,
                     sum(1 for i in b.indices if verdicts[i].green))
    if sink is not None:
        for v in verdicts:
            sink.write_record("chaos_scenario", v.to_json())
    result = CampaignResult(
        verdicts=verdicts,
        manifest_path=getattr(sink, "path", None),
        buckets=[{"scenarios": b.size, "n_members": b.params.n_members,
                  "horizon": b.horizon} for b in buckets],
    )
    if sink is not None:
        sink.write_record("chaos_verdict", result.summary())
    return result


def run_weakened_slice(buckets: List[ScenarioBucket],
                       capacity: int = cmonitor.DEFAULT_CAPACITY,
                       knobs_fn=None):
    """The fuzz COVERAGE arm: rerun every bucket holding a
    completeness-promising row (finite ``MonitorSpec.complete_by``) on
    the deliberately-weakened build (``knobs_fn``, default
    :func:`weakened_knobs`) and count what the fuzzer finds there —
    shared by ``bench.py --fuzz`` and ``experiments/fuzz_campaign.py``
    so the slice predicate and rerun protocol cannot drift.

    Because the weakening is dynamic Knobs data, every rerun REUSES the
    healthy batch's compiled programs (chaos/monitor.run_monitored_batch
    docstring).  Returns ``(cov_indices, weak_counts, first_red)``:
    the set of completeness-promising scenario indices, the summed
    per-code violation totals (np.int64 [N_CODES]) over that slice on
    the weakened build, and the first red row's index (None if the
    weakened arm found nothing)."""
    import jax
    import jax.numpy as jnp

    if knobs_fn is None:
        knobs_fn = weakened_knobs
    int32_max = int(np.iinfo(np.int32).max)
    cov = {
        i
        for b in buckets
        for i, (_, spec) in zip(b.indices, b.members)
        if int(np.asarray(spec.complete_by).min()) < int32_max
    }
    weak_counts = np.zeros(cmonitor.N_CODES, dtype=np.int64)
    first_red = None
    for b in buckets:
        if not any(i in cov for i in b.indices):
            continue
        kn_w = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs),
            *[knobs_fn(s, b.params) for s in b.scenarios])
        mon_w, _ = run_bucket(b, capacity=capacity, knobs=kn_w)
        for i, mon in zip(b.indices, cmonitor.unstack_monitor(mon_w)):
            if i not in cov:
                continue
            counts = np.asarray(mon.code_counts, dtype=np.int64)
            weak_counts += counts
            if first_red is None and counts.sum() > 0:
                first_red = i
    return cov, weak_counts, first_red


def weakened_knobs(scenario: "cscenarios.Scenario",
                   params: "swim.SwimParams") -> "swim.Knobs":
    """The deliberately-WEAKENED build of the fuzz coverage arm
    (``build_buckets``' ``knobs_fn`` signature): suspicion timers
    stretched far past any campaign horizon (2^20 rounds), so
    suspicions never mature into DEAD verdicts — permanently crashed
    members are never removed, and every scenario that promises
    completeness (finite ``MonitorSpec.complete_by``) must trip
    COMPLETENESS past its deadline.  The fuzzer finding exactly these
    planted violations (and the healthy build finding none) is the
    coverage gate of ``bench.py --fuzz``.

    A dynamic-knobs weakening on purpose: Knobs are traced data, so the
    weakened rerun REUSES the healthy batch's compiled program
    (chaos/monitor.run_monitored_batch docstring)."""
    import jax.numpy as jnp

    del scenario  # same weakening for every row; the hook passes it
    return dataclasses.replace(
        swim.Knobs.from_params(params),
        suspicion_rounds=jnp.int32(1 << 20))


def alarm_breach_knobs(scenario: "cscenarios.Scenario",
                       params: "swim.SwimParams") -> "swim.Knobs":
    """The alarm drill's BREACH arm (bench.py --alarms): probe every
    round (``ping_every=1``) instead of the campaign cadence.  Each
    probe into the drill scenario's loss pulse is an independent chance
    to falsely suspect a live member, and refutation gossip (the
    target's outbound links stay clean) re-arms the observer within a
    round or two — so doubling the probe cadence multiplies the
    ``false_positive_observer_rate`` by ~1.5x measured exactly while
    the pulse holds, and only then (both arms are exactly zero outside
    it).  Deliberately does NOT touch ``suspicion_rounds``: shortening
    it INVERTS the drill — false suspicions mature into false deaths on
    the first onset, the dead targets stop being probed, and the onset
    rate collapses below the healthy arm's.

    Dynamic Knobs data like :func:`weakened_knobs`, and for the same
    reason: the breach arm reruns the healthy arm's compiled program —
    the drill's A/B costs zero extra compiles."""
    import jax.numpy as jnp

    del scenario  # one amplification for every drill scenario
    return dataclasses.replace(
        swim.Knobs.from_params(params),
        ping_every=jnp.int32(1))


# --------------------------------------------------------------------------
# Minimizing reducer: violating scenario -> one-line repro
# --------------------------------------------------------------------------


@dataclasses.dataclass
class MinimizedRepro:
    """:func:`minimize`'s result: the shrunken (still-violating)
    scenario, its red verdict, and the executable one-line repro."""

    scenario: "cscenarios.Scenario"
    verdict: ScenarioVerdict
    dropped_ops: int
    codes: List[str]
    # Extra run_scenario kwargs the replay needs, verbatim (e.g. the
    # weakened-build knobs) — without it a violation found through
    # minimize()'s ``run=`` hook would print a line that replays the
    # HEALTHY build and reproduces nothing.
    repro_args: str = ""

    def repro(self) -> str:
        """One line that reproduces the minimized violation (everything
        resolves under ``from scalecube_cluster_tpu import chaos``)."""
        ops = ", ".join(f"chaos.{op!r}" for op in self.scenario.ops)
        trail = "," if len(self.scenario.ops) == 1 else ""
        extra = (f", extra_slack={self.scenario.extra_slack}"
                 if self.scenario.extra_slack else "")
        suffix = f", {self.repro_args}" if self.repro_args else ""
        return (f"chaos.run_scenario(chaos.Scenario("
                f"name={self.scenario.name!r}, "
                f"n_members={self.scenario.n_members}, "
                f"horizon={self.scenario.horizon}, ops=({ops}{trail}), "
                f"loss_probability={self.scenario.loss_probability}"
                f"{extra}), seed={self.verdict.seed}, "
                f"delivery={self.verdict.delivery!r}{suffix})")


def minimize(verdict: ScenarioVerdict, run=None, log=None,
             repro_args: str = "") -> MinimizedRepro:
    """Greedy minimizing reducer: drop ops from a violating scenario one
    at a time (restarting the sweep after every successful drop) while
    EVERY one of the verdict's violating codes still reproduces under
    the same run seed/delivery — the emitted repro replays the whole
    ``codes`` list, never just its loudest member — down to a local
    minimum: usually the single guilty op, or one op per code when the
    codes have different culprits.

    ``run(scenario) -> ScenarioVerdict`` overrides the replay (default:
    sequential :func:`run_scenario` with the verdict's seed/delivery) —
    the hook that lets the weakened coverage arm minimize under its
    weakened knobs.  When ``run`` departs from the default, pass the
    departure as ``repro_args`` (verbatim ``run_scenario`` kwargs, e.g.
    ``"knobs=lambda p: chaos.weakened_knobs(None, p)"``) so the emitted
    one-line repro actually replays the failing build.  A candidate
    whose op-drop breaks DSL composition (build-time validation) is
    skipped, never fatal; a drop that surfaces NEW codes keeps only the
    original codes as the reproduction predicate.
    """
    codes = [c for c, d in verdict.verdict["codes"].items()
             if d["violations"] > 0]
    if not codes:
        raise ValueError("minimize() needs a violating verdict "
                         "(all code totals are zero)")
    if run is None:
        def run(scen):
            return run_scenario(scen, seed=verdict.seed,
                                delivery=verdict.delivery)

    cur_scen, cur_verdict = verdict.scenario, verdict
    dropped = 0
    progress = True
    while progress and len(cur_scen.ops) > 1:
        progress = False
        for j in range(len(cur_scen.ops)):
            cand = dataclasses.replace(
                cur_scen, ops=cur_scen.ops[:j] + cur_scen.ops[j + 1:],
                name=f"{verdict.scenario.name}-min",
                seed=None, severity=None)
            try:
                v = run(cand)
            except (ValueError, TypeError):
                continue        # the drop broke DSL composition: keep op
            if all(v.verdict["codes"][c]["violations"] > 0
                   for c in codes):
                cur_scen, cur_verdict = cand, v
                dropped += 1
                progress = True
                if log is not None:
                    log.info("minimize: dropped op %d -> %d op(s) left",
                             j, len(cand.ops))
                break
    return MinimizedRepro(scenario=cur_scen, verdict=cur_verdict,
                          dropped_ops=dropped, codes=codes,
                          repro_args=repro_args)


# --------------------------------------------------------------------------
# Oracle cross-validation (small N, crash/leave schedules)
# --------------------------------------------------------------------------


def _crash_leave_schedule(scenario: "cscenarios.Scenario"):
    """(crashes, leaves) when every op is oracle-expressible AND drives
    to quiescence (permanent, or revived only after removal completes);
    None otherwise.  crashes: [(node, at, until)], leaves: [(node, at)].
    """
    params = campaign_params(scenario)
    crashes, leaves = [], []
    for op in scenario.ops:
        if isinstance(op, cscenarios.Leave):
            leaves.append((op.node, op.at_round))
        elif isinstance(op, (cscenarios.Crash, cscenarios.CrashBurst)):
            nodes = ([op.node] if isinstance(op, cscenarios.Crash)
                     else list(op.nodes))
            if op.until_round < INT32_MAX:
                # Short crashes don't quiesce (which observers suspected
                # before the revival is seed-dependent on both layers).
                if (op.until_round - op.at_round
                        < 2 * params.suspicion_rounds + 16):
                    return None
            crashes.extend((v, op.at_round, op.until_round)
                           for v in nodes)
        else:
            return None
    if scenario.loss_probability:
        return None
    return crashes, leaves


def _oracle_cluster(seed: int, n: int, cfg, round_ms: int):
    """Warmed-up n-member oracle cluster + attached trace collector —
    the shared bring-up of both cross-validations.  Returns
    ``(sim, clusters, collector)``."""
    from scalecube_cluster_tpu.oracle import Cluster, Simulator
    from scalecube_cluster_tpu.telemetry.events import OracleTraceCollector

    sim = Simulator(seed=seed)
    clusters = [Cluster.join(sim, config=cfg, alias="m0")]
    for i in range(1, n):
        clusters.append(Cluster.join(sim, seeds=[clusters[0].address],
                                     config=cfg, alias=f"m{i}"))
    sim.run_for(4_000)
    assert all(len(c.members()) == n for c in clusters), \
        "oracle warmup incomplete"
    collector = OracleTraceCollector(sim, round_ms,
                                     index_of=lambda m: int(m.id[1:]))
    for i, c in enumerate(clusters):
        collector.watch(c, observer_index=i)
    return sim, clusters, collector


def cross_validate(scenario: "cscenarios.Scenario", seed: int = 0,
                   delivery: str = "shift",
                   round_ms: int = 100) -> Optional[dict]:
    """Replay an expressible scenario on the event-driven oracle and
    diff SUSPECTED/REMOVED (and post-revival ADDED) key sets per victim
    against the model's on-device trace, over continuously-live
    observers.  Returns the diff digest (``agree`` bool + per-victim
    only_model/only_oracle keys), or None when the scenario isn't
    oracle-expressible.
    """
    import jax

    from scalecube_cluster_tpu.telemetry import trace as ttrace
    from scalecube_cluster_tpu.telemetry.events import (
        TraceEventType, event_key_set,
    )

    sched = _crash_leave_schedule(scenario)
    if sched is None:
        return None
    crashes, leaves = sched
    n, horizon = scenario.n_members, scenario.horizon
    cfg = campaign_config()

    # --- oracle side: same schedule, crash = full link blockade -------
    sim, clusters, collector = _oracle_cluster(seed, n, cfg, round_ms)

    def block(victim):
        rest = [c for c in clusters if c is not clusters[victim]]
        clusters[victim].network_emulator.block(
            [c.address for c in rest])
        for c in rest:
            c.network_emulator.block(clusters[victim].address)

    def unblock(victim):
        clusters[victim].network_emulator.unblock_all()
        for c in clusters:
            c.network_emulator.unblock(clusters[victim].address)

    events = {}
    for r in range(horizon):
        for v, at, until in crashes:
            if r == at:
                block(v)
            if until < INT32_MAX and r == until:
                unblock(v)
        for v, at in leaves:
            if r == at:
                clusters[v].shutdown()
        sim.run_for(round_ms)

    # --- model side ---------------------------------------------------
    params = campaign_params(scenario, delivery=delivery)
    world, _ = scenario.build(params)
    _, tel, _ = swim.run_traced(jax.random.key(seed), params, world,
                                horizon)
    model_events = ttrace.decode_events(tel)

    downers = {v for v, _, _ in crashes} | {v for v, _ in leaves}
    observers = [i for i in range(n) if i not in downers]
    per_victim = {}
    agree = True
    for v, at, until in crashes:
        types = [TraceEventType.SUSPECTED, TraceEventType.REMOVED]
        if until < INT32_MAX:
            types.append(TraceEventType.ADDED)
        kw = dict(types=types, subjects=[v], observers=observers,
                  min_round=at)
        mk = event_key_set(model_events, **kw)
        ok = event_key_set(collector.events, **kw)
        per_victim[v] = {"only_model": sorted(mk - ok),
                         "only_oracle": sorted(ok - mk)}
        agree &= mk == ok
    for v, at in leaves:
        kw = dict(types=[TraceEventType.REMOVED], subjects=[v],
                  observers=observers)
        mk = event_key_set(model_events, **kw)
        ok = event_key_set(collector.events, **kw)
        per_victim[v] = {"only_model": sorted(mk - ok),
                         "only_oracle": sorted(ok - mk)}
        agree &= mk == ok
    return {
        "agree": agree,
        "observers": len(observers),
        "victims": {str(k): d for k, d in per_victim.items()},
    }


def _churn_join_schedule(scenario: "cscenarios.Scenario"):
    """(crashes [(node, at)], joins [(slot, at)]) when every op is a
    PERMANENT crash schedule or an arrival storm (ChurnStorm with
    joins, the churn_growth_scenario shape) or an explicit Join, on a
    lossless network; None otherwise.  The oracle replay below models
    crashes as permanent blockades and joins as brand-new Cluster.join
    members, so revives and network ops are out of scope."""
    if scenario.loss_probability:
        return None
    crashes, joins = [], []
    for op in scenario.ops:
        if isinstance(op, cscenarios.Crash):
            if op.until_round < INT32_MAX:
                return None
            crashes.append((op.node, op.at_round))
        elif isinstance(op, cscenarios.CrashBurst):
            if op.until_round < INT32_MAX:
                return None
            crashes.extend((v, op.at_round) for v in op.nodes)
        elif isinstance(op, cscenarios.Join):
            joins.append((op.slot, op.at_round))
        elif isinstance(op, cscenarios.ChurnStorm):
            if op.down_rounds:
                return None
            for w in range(op.n_waves):
                at = op.start_round + w * op.wave_every
                crashes.extend(
                    (v, at)
                    for v in op.nodes[w * op.wave_size:
                                      (w + 1) * op.wave_size])
            crashes.extend((v, 0) for v in op.arrivals)
            if op.join_wave_size:
                joins.extend(op._join_schedule())
        else:
            return None
    if not joins:
        return None
    return crashes, joins


def cross_validate_churn(scenario: "cscenarios.Scenario", seed: int = 0,
                         delivery: str = "shift",
                         round_ms: int = 100) -> Optional[dict]:
    """Replay a net-positive churn storm — permanent crashes plus
    MID-RUN JOINS into the recycled slots — on the event-driven oracle
    and diff the timing-free per-slot event key sets against the
    model's on-device trace.

    Oracle side: a crash is the permanent full link blockade (the
    cross_validate convention); a JOIN is a genuine mid-run
    ``Cluster.join`` of a BRAND-NEW member (fresh random identity —
    aliased ``j<slot>`` so both identities of a slot map to the same
    integer index) seeded at a stable member, exactly the reference's
    arrival path.  Model side: the same scenario through the open-world
    plane (``campaign_params`` auto-enables it), where the slot's
    identity-epoch lane admits the new member; the model's JOINED
    events are the oracle's ADDED events for the new identity, so the
    diff NORMALIZES JOINED -> ADDED before comparing (the slot-level
    trace cannot carry the oracle's random member ids; the epoch lane
    is its identity axis — telemetry/events.TraceEventType docstring).

    Per crashed slot the SUSPECTED/REMOVED key sets must match; per
    joined slot the post-join ADDED key set must match (the new
    identity at incarnation 0, learned by every continuously-live
    observer).  Observers are restricted to members that never crash
    or join.  Returns the diff digest, or None when the scenario isn't
    expressible.
    """
    import jax

    from scalecube_cluster_tpu.oracle import Cluster
    from scalecube_cluster_tpu.telemetry import trace as ttrace
    from scalecube_cluster_tpu.telemetry.events import (
        TraceEventType, event_key_set,
    )

    sched = _churn_join_schedule(scenario)
    if sched is None:
        return None
    crashes, joins = sched
    n, horizon = scenario.n_members, scenario.horizon
    cfg = campaign_config()

    downers = {v for v, _ in crashes}
    joiners = {s for s, _ in joins}
    observers = [i for i in range(n) if i not in downers | joiners]
    stable_seed = observers[0]

    # --- oracle side --------------------------------------------------
    # (_oracle_cluster's index_of strips the one-char alias prefix, so
    # the joined "j<slot>" identities map to the same slot index as the
    # original "m<slot>" members.)
    sim, clusters, collector = _oracle_cluster(seed, n, cfg, round_ms)

    def block(victim):
        rest = [c for c in clusters if c is not clusters[victim]]
        clusters[victim].network_emulator.block(
            [c.address for c in rest])
        for c in rest:
            c.network_emulator.block(clusters[victim].address)

    for r in range(horizon):
        for v, at in crashes:
            if r == at:
                block(v)
        for s, at in joins:
            if r == at:
                newcomer = Cluster.join(
                    sim, seeds=[clusters[stable_seed].address],
                    config=cfg, alias=f"j{s}")
                collector.watch(newcomer, observer_index=s)
                clusters[s] = newcomer
        sim.run_for(round_ms)

    # --- model side (open-world plane ON via campaign_params) ---------
    params = campaign_params(scenario, delivery=delivery)
    world, _ = scenario.build(params)
    _, tel, _ = swim.run_traced(jax.random.key(seed), params, world,
                                horizon)
    model_events = [
        (dataclasses.replace(e, event_type=TraceEventType.ADDED)
         if e.event_type == TraceEventType.JOINED else e)
        for e in ttrace.decode_events(tel)
    ]

    per_slot = {}
    agree = True
    for v, at in crashes:
        types = [TraceEventType.SUSPECTED, TraceEventType.REMOVED]
        kw = dict(types=types, subjects=[v], observers=observers,
                  min_round=at)
        mk = event_key_set(model_events, **kw)
        ok = event_key_set(collector.events, **kw)
        per_slot[f"crash:{v}"] = {"only_model": sorted(mk - ok),
                                  "only_oracle": sorted(ok - mk)}
        agree &= mk == ok
    for s, at in joins:
        kw = dict(types=[TraceEventType.ADDED], subjects=[s],
                  observers=observers, min_round=at)
        mk = event_key_set(model_events, **kw)
        ok = event_key_set(collector.events, **kw)
        per_slot[f"join:{s}"] = {"only_model": sorted(mk - ok),
                                 "only_oracle": sorted(ok - mk)}
        agree &= mk == ok
    return {
        "agree": agree,
        "observers": len(observers),
        "crashes": len(crashes),
        "joins": len(joins),
        "slots": per_slot,
    }


def _single_partition(scenario: "cscenarios.Scenario"):
    """The scenario's one RollingPartition op when the partition/heal
    schedule is oracle-expressible (exactly one split/heal cycle, no
    other ops, no background loss); None otherwise."""
    if scenario.loss_probability:
        return None
    if len(scenario.ops) != 1:
        return None
    op = scenario.ops[0]
    if not isinstance(op, cscenarios.RollingPartition):
        return None
    if op.n_cycles != 1:
        return None
    return op


def cross_validate_partition(scenario: "cscenarios.Scenario", seed: int = 0,
                             delivery: str = "shift",
                             round_ms: int = 100,
                             sync_interval: Optional[int] = None
                             ) -> Optional[dict]:
    """Replay a single-cycle RollingPartition on the event-driven
    oracle — split = blocking every cross-half link both ways, heal =
    unblocking — and diff the timing-free SUSPECTED/REMOVED/ADDED key
    sets per member against the model's on-device trace, over
    opposite-half observers.  The model runs WITH the SYNC anti-entropy
    plane (``sync_interval`` rounds; default = the campaign preset's
    oracle sync interval quantized to rounds), so the post-heal ADDED
    events are exactly the SYNC-recovered members on both layers: the
    oracle re-adds removed members through its doSync/syncAck full-table
    exchange (oracle/membership._sync_membership), the model through the
    plane's paired exchange reopening the tombstone cells
    (models/sync.py).  Returns the diff digest or None when the
    scenario isn't expressible.

    The split must be long enough to QUIESCE (chaos/scenarios.
    quiesce_bound) — both layers then reach the same terminal key sets:
    every opposite-half observer suspects, removes, and post-heal
    re-adds every cross member at incarnation 0.
    """
    import jax

    from scalecube_cluster_tpu.telemetry import trace as ttrace
    from scalecube_cluster_tpu.telemetry.events import (
        TraceEventType, event_key_set,
    )

    op = _single_partition(scenario)
    if op is None:
        return None
    n, horizon = scenario.n_members, scenario.horizon
    cfg = campaign_config()
    if sync_interval is None:
        sync_interval = max(1, int(round(cfg.sync_interval / round_ms)))
    split_at = op.from_round
    heal_at = op.from_round + op.phase_rounds
    # Halves as RollingPartition.apply compiles cycle 0: partition id 1
    # for ids below n//2 — two contiguous ranges.
    half_a = list(range(n // 2))
    half_b = list(range(n // 2, n))

    # --- oracle side --------------------------------------------------
    sim, clusters, collector = _oracle_cluster(seed, n, cfg, round_ms)

    def set_split(active: bool):
        for a in half_a:
            for b in half_b:
                if active:
                    clusters[a].network_emulator.block(
                        [clusters[b].address])
                    clusters[b].network_emulator.block(
                        [clusters[a].address])
                else:
                    clusters[a].network_emulator.unblock(
                        clusters[b].address)
                    clusters[b].network_emulator.unblock(
                        clusters[a].address)

    for r in range(horizon):
        if r == split_at:
            set_split(True)
        if r == heal_at:
            set_split(False)
        sim.run_for(round_ms)

    # --- model side (anti-entropy plane ON) ---------------------------
    params = campaign_params(scenario, delivery=delivery,
                             sync_interval=sync_interval)
    world, _ = scenario.build(params)
    _, tel, _ = swim.run_traced(jax.random.key(seed), params, world,
                                horizon)
    model_events = ttrace.decode_events(tel)

    per_victim = {}
    agree = True
    for v in range(n):
        observers = half_b if v in half_a else half_a
        kw = dict(
            types=[TraceEventType.SUSPECTED, TraceEventType.REMOVED,
                   TraceEventType.ADDED],
            subjects=[v], observers=observers, min_round=split_at,
        )
        mk = event_key_set(model_events, **kw)
        ok = event_key_set(collector.events, **kw)
        recovered = {k for k in mk if k[2] == int(TraceEventType.ADDED)}
        per_victim[v] = {"only_model": sorted(mk - ok),
                         "only_oracle": sorted(ok - mk),
                         "sync_recovered_keys": len(recovered)}
        agree &= mk == ok
    return {
        "agree": agree,
        "halves": [len(half_a), len(half_b)],
        "sync_interval": sync_interval,
        "victims": {str(k): d for k, d in per_victim.items()},
    }


def _metadata_push_schedule(scenario: "cscenarios.Scenario"):
    """Flat ``[(node, key, value, at_round)]`` when every op is a
    metadata push on a lossless network (ConfigPush / StagedRollout —
    membership stays quiet, which is what makes per-member terminal KV
    parity exact rather than timing-dependent); None otherwise."""
    if scenario.loss_probability or not scenario.ops:
        return None
    pushes = []
    for op in scenario.ops:
        if isinstance(op, (cscenarios.ConfigPush,
                           cscenarios.StagedRollout)):
            pushes.extend(op.push_schedule())
        else:
            return None
    return sorted(pushes, key=lambda p: p[3])


def cross_validate_metadata(scenario: "cscenarios.Scenario",
                            seed: int = 0, delivery: str = "shift",
                            round_ms: int = 100) -> Optional[dict]:
    """Replay a pure config-push scenario on the event-driven oracle —
    each push is the reference's ``Cluster.update_metadata`` (an
    incarnation-bumping local write whose new words peers re-fetch,
    oracle/cluster.py) — and require PER-MEMBER CONVERGED-KV PARITY:
    after the horizon, every observer on BOTH layers must hold exactly
    the last-pushed value for every (owner, key), and the two layers'
    terminal tables must agree.  This is the ground-truth check for the
    jit KV plane's LWW merge (models/metadata.py): the oracle converges
    by demand-fetch on incarnation bumps, the model by versioned
    piggyback + anti-entropy, and on a quiet lossless network both must
    land on the same terminal table — any model cell stuck below the
    last write (a lost version) or above it (a resurrected word) breaks
    parity.  Returns the diff digest, or None when the scenario isn't
    expressible (any non-push op, or background loss)."""
    import jax

    sched = _metadata_push_schedule(scenario)
    if sched is None:
        return None
    n, horizon = scenario.n_members, scenario.horizon
    cfg = campaign_config()

    # --- oracle side --------------------------------------------------
    sim, clusters, _ = _oracle_cluster(seed, n, cfg, round_ms)
    for r in range(horizon):
        for node, key, value, at in sched:
            if r == at:
                clusters[node].update_metadata_property(
                    f"k{key}", str(value))
        sim.run_for(round_ms)

    # Terminal expectation: last push wins per (owner, key) — the LWW
    # fixed point both layers must reach on a quiet network.
    expected: dict = {}
    for node, key, value, _ in sched:
        expected.setdefault(node, {})[key] = value

    # --- model side (metadata plane ON via campaign_params) -----------
    params = campaign_params(scenario, delivery=delivery)
    world, _ = scenario.build(params)
    state, _ = swim.run(jax.random.key(seed), params, world, horizon)
    md = np.asarray(state.md)            # [n, K=n, M], full view

    agree = True
    per_push = {}
    for owner in sorted(expected):
        for key, value in sorted(expected[owner].items()):
            model_vals = [
                int(np.asarray(metadata.word_value(md[o, owner, key])))
                for o in range(n)
            ]
            oracle_vals = []
            for o in range(n):
                mem = next(m for m in clusters[o].members()
                           if int(m.id[1:]) == owner)
                kv = clusters[o].metadata(mem) or {}
                oracle_vals.append(kv.get(f"k{key}"))
            model_div = sum(v != value for v in model_vals)
            oracle_div = sum(v != str(value) for v in oracle_vals)
            per_push[f"{owner}:k{key}"] = {
                "value": value,
                "model_divergent": model_div,
                "oracle_divergent": oracle_div,
            }
            agree &= model_div == 0 and oracle_div == 0
    return {
        "agree": agree,
        "observers": n,
        "pushes": len(sched),
        "per_push": per_push,
    }
