"""Chaos engineering for the dense SWIM model: adversarial fault
campaigns as a first-class, on-device workload.

Two halves (ISSUE 3):

  - :mod:`scalecube_cluster_tpu.chaos.scenarios` — the declarative
    fault-scenario DSL (churn storms, flapping links, rolling
    partitions, correlated crash bursts, asymmetric brownouts) that
    compiles to the existing ``SwimWorld``/``LinkFaults`` schedule
    arrays, plus the seeded severity-tiered campaign generator (any
    failing scenario is a one-line repro).
  - :mod:`scalecube_cluster_tpu.chaos.monitor` — the in-jit invariant
    monitor: a fixed-capacity violation buffer carried through the scan
    (the telemetry/trace.py pattern) evaluating the paper's
    safety/liveness invariants every round on device, recording
    first-violation evidence lanes with overflow counted — a violated
    run COMPLETES and reports (graceful degradation), it never crashes.

:mod:`scalecube_cluster_tpu.chaos.campaign` drives generated scenarios
through the monitored run, cross-validates against the event-driven
oracle at small N, and emits verdict manifests through the
telemetry/sink.py JSONL pipeline (``bench.py --chaos``,
``experiments/chaos_campaign.py``).
"""

from scalecube_cluster_tpu.chaos.monitor import (  # noqa: F401
    DEFAULT_CAPACITY,
    InvariantCode,
    InvariantViolation,
    MonitorSpec,
    MonitorState,
    decode_violations,
    run_monitored,
    run_monitored_batch,
    unstack_monitor,
    verdict,
)
from scalecube_cluster_tpu.chaos.scenarios import (  # noqa: F401
    Brownout,
    ChurnStorm,
    Crash,
    CrashBurst,
    FlappingLink,
    Leave,
    LinkLoss,
    RollingPartition,
    SEVERITIES,
    Scenario,
    asymmetric_degradation,
    asymmetric_degraded_range,
    completeness_bound,
    generate_campaign,
    generate_fuzz_campaign,
    generate_scenario,
)
from scalecube_cluster_tpu.chaos.campaign import (  # noqa: F401
    CampaignResult,
    MinimizedRepro,
    ScenarioBucket,
    ScenarioVerdict,
    build_buckets,
    campaign_config,
    cross_validate,
    minimize,
    run_bucket,
    run_campaign,
    run_campaign_vmapped,
    run_scenario,
    weakened_knobs,
)
