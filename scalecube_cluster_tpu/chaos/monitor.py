"""In-jit invariant monitor: the paper's guarantees checked every round
ON DEVICE, with graceful degradation.

The SWIM paper's headline properties — no false removal of a non-faulty
member under a lossless network, monotone incarnations, bounded
suspicion timers, time-bounded strong completeness after a permanent
crash (PAPER.md) — were previously checked only by host-side numpy at
N<=40 (tests/test_fuzz.py).  This module evaluates them INSIDE the
``lax.scan`` that runs the protocol, so the same checks ride along at
any scale the model simulates: the monitor state is a fixed-capacity
violation buffer carried through the scan (the telemetry/trace.py
pattern — fused elementwise derivation, one cumsum + one scatter,
overflow counted, never silent).

Invariant codes (:class:`InvariantCode`; lane values are stable):

  FALSE_SUSPICION   a live observer newly marks a live subject SUSPECT
                    although the scenario has no loss, link faults,
                    delays or partitions — the "no false suspicion
                    absent faults/loss" safety property.  Enabled per
                    scenario (``MonitorSpec.check_false_suspicion``);
                    under real network faults false suspicion is
                    legitimate protocol behavior, not a violation.
  INC_REGRESSION    a stored LIVE (ALIVE/SUSPECT) record's incarnation
                    decreased without the record turning DEAD, or a
                    node's own incarnation decreased: the
                    monotone-incarnation property per cell.  (A DEAD
                    winner may legally carry a lower incarnation —
                    isOverrides case 3 — and a stored tombstone gates
                    like ABSENT, so delete-then-re-add may restart the
                    cell at any incarnation; records.py.)
  TIMER_BOUND       a live observer's suspicion-timer contract broke:
                    a pending timer on a non-SUSPECT entry, a SUSPECT
                    entry without a timer, an expired timer that did
                    not fire, or a deadline beyond
                    round + suspicion_rounds.
  WIRE_SATURATION   the carry holds an incarnation above the active
                    wire key format's saturation point (or negative) —
                    past it wire and table silently diverge at the
                    merge gate.  The bound is per FORMAT, derived from
                    the one ops/delivery.WIRE_FORMATS table via
                    models/swim._wire_inc_sat (2^29-1 wide, 8191
                    wire16, 32767 wire24 under the compact carry; the
                    open-world epoch field lowers the wire caps) — a
                    clamped run sits exactly AT the cap under
                    saturation pressure and stays green
                    (tests/test_wire_saturation.py).
  COMPLETENESS      time-bounded completeness: past the scenario's
                    per-subject ``complete_by`` deadline, an eligible
                    observer (continuously alive since the subject's
                    fault) still holds ALIVE/SUSPECT about a
                    permanently crashed/left subject.
  POST_HEAL_DIVERGENCE  past the scenario's post-heal agreement round
                    (``MonitorSpec.agree_from`` — last heal +
                    sync_interval + dissemination bound), a live
                    observer's record of some subject still differs
                    from the live consensus: the SYNC anti-entropy
                    plane's bounded re-convergence contract
                    (models/sync.py).  Only promised when the plane is
                    on and the scenario's faults quiesce before the
                    heal (chaos/scenarios.Scenario.build).
  NO_RESURRECTION   past a recycled slot's join-propagation deadline
                    (``MonitorSpec.join_known_by``), a live observer
                    still holds an ALIVE/SUSPECT record attributed to
                    a DEAD identity epoch (the carry's ``epoch`` lane
                    < the slot's ground-truth epoch,
                    ``SwimWorld.epoch_at``) — a dead epoch's record
                    living in a table, the naive-slot-reuse
                    resurrection hazard the open-world epoch guard
                    exists to kill (models/swim.SwimParams.open_world;
                    the instrumented naive arm keeps the lane so this
                    code can COUNT its failures).
  JOIN_COMPLETENESS past the same deadline, an eligible observer
                    (continuously alive since the join) does NOT hold
                    the joined member ALIVE/SUSPECT at its true epoch
                    while the member is ground-truth alive: a joined
                    member must become globally known within the
                    dissemination bound (the ADDED-completeness dual
                    of COMPLETENESS; in the naive arm the old
                    occupant's tombstone killing the new member's
                    records lands here).

Evidence policy: per code, the LANES record the violating cells of the
first round that code trips (with overflow counted in ``dropped``);
every later violating cell still counts in ``code_counts`` and the
per-round totals, so the buffer cannot be flooded by a persistent
violation re-firing each round — first-violation evidence plus exact
totals, the graceful-degradation contract: a violated run completes
and reports, it never crashes.
"""

from __future__ import annotations

import dataclasses
import enum
from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from scalecube_cluster_tpu import records
from scalecube_cluster_tpu.models import swim
from scalecube_cluster_tpu.models import sync as msync

INT32_MAX = jnp.iinfo(jnp.int32).max

# Violation-lane capacity: one round's worth of first-violation cells
# per code is N*K worst case, but real violations cluster; 4096 lanes
# (80 KB) is free next to any carry and far above the evidence a
# diagnosable failure needs — overflow is counted, never silent.
DEFAULT_CAPACITY = 1 << 12

_N_LANES = 5  # (round, observer, subject, code, detail)


class InvariantCode(enum.IntEnum):
    """Violation kinds (module docstring; lane values stable — do not
    renumber)."""

    FALSE_SUSPICION = 0
    INC_REGRESSION = 1
    TIMER_BOUND = 2
    WIRE_SATURATION = 3
    COMPLETENESS = 4
    POST_HEAL_DIVERGENCE = 5
    NO_RESURRECTION = 6
    JOIN_COMPLETENESS = 7


N_CODES = len(InvariantCode)


# --------------------------------------------------------------------------
# Carried state + the static-per-scenario spec
# --------------------------------------------------------------------------


@dataclasses.dataclass
class MonitorState:
    """Scan-carried violation evidence (module docstring).

    ``lanes[i] = (round, observer, subject, code, detail)`` for
    i < ``count``; ``detail`` is code-specific (incarnation, deadline,
    or held status).  ``code_counts[c]`` totals EVERY violating cell of
    code c across the run (not just recorded ones);
    ``code_first_round[c]`` is the first round code c tripped
    (INT32_MAX = never).  A run is green iff ``code_counts`` is all
    zero.
    """

    lanes: jnp.ndarray              # [capacity, 5] int32
    count: jnp.ndarray              # int32 scalar
    dropped: jnp.ndarray            # int32 scalar (evidence overflow)
    code_counts: jnp.ndarray        # [N_CODES] int32
    code_first_round: jnp.ndarray   # [N_CODES] int32

    @property
    def capacity(self) -> int:
        return self.lanes.shape[0]

    @staticmethod
    def init(capacity: int = DEFAULT_CAPACITY) -> "MonitorState":
        return MonitorState(
            lanes=jnp.full((capacity, _N_LANES), -1, dtype=jnp.int32),
            count=jnp.int32(0),
            dropped=jnp.int32(0),
            code_counts=jnp.zeros((N_CODES,), dtype=jnp.int32),
            code_first_round=jnp.full((N_CODES,), INT32_MAX,
                                      dtype=jnp.int32),
        )

    def to_arrays(self, prefix: str = "monitor/") -> dict:
        """Flat host-side ``{prefix<field>: np.ndarray}`` dict — the
        checkpoint-payload form the resilient supervisor persists, so a
        preemption cannot lose accumulated violation evidence
        (resilience/supervisor.py)."""
        return {
            f"{prefix}{f.name}": np.asarray(getattr(self, f.name))
            for f in dataclasses.fields(self)
        }

    @staticmethod
    def from_arrays(arrays: dict,
                    prefix: str = "monitor/") -> "MonitorState":
        """Inverse of :meth:`to_arrays` (device transfer included) —
        resumes the monitor mid-run as ``run_monitored``'s ``monitor``
        argument."""
        return MonitorState(**{
            f.name: jnp.asarray(arrays[f"{prefix}{f.name}"])
            for f in dataclasses.fields(MonitorState)
        })


jax.tree_util.register_dataclass(
    MonitorState,
    data_fields=["lanes", "count", "dropped", "code_counts",
                 "code_first_round"],
    meta_fields=[],
)


@dataclasses.dataclass
class MonitorSpec:
    """What the scenario promises, so the monitor knows what to enforce.

    ``complete_by`` [K] int32: per-subject completeness deadline —
    by that round every eligible observer must have dropped the subject
    (INT32_MAX = completeness unchecked for that subject; scenarios
    compute deadlines from their fault/disruption schedules —
    chaos/scenarios.Scenario.build).  ``agree_from`` int32 scalar: the
    post-heal agreement deadline — from that round on, every live
    observer's record of every subject must match the live consensus
    (the SYNC anti-entropy plane's re-convergence contract,
    models/sync.py; INT32_MAX = no agreement promise, the default —
    scenarios only promise it when the plane is on and the heal is
    quiesced).  ``check_agreement`` is ``agree_from``'s static
    (treedef) twin: False compiles the per-round divergence reduction
    out entirely — ``agree_from`` is traced data XLA cannot fold, so
    without the static flag every plane-off monitored run would pay
    the [N, K] consensus reduction for a check that can never trip
    (the ``check_false_suspicion`` pattern).
    ``check_false_suspicion`` is a
    static (treedef) flag: True only when the scenario's network is
    pristine, where any new suspicion of a live subject is a safety
    violation.

    ``join_known_by`` [K] int32: per-subject JOIN-propagation deadline
    (INT32_MAX = unchecked) — past it the open-world codes
    (NO_RESURRECTION / JOIN_COMPLETENESS) enforce that the joined
    identity is globally known and no dead epoch's record survives as
    live; scenarios derive it from the join schedule
    (``Scenario.build``: join round + completeness bound).
    ``check_joins`` is its static (treedef) twin, the
    ``check_agreement`` pattern — False compiles both [N, K] join
    reductions out entirely.
    """

    complete_by: jnp.ndarray
    agree_from: jnp.ndarray = dataclasses.field(
        default_factory=lambda: jnp.int32(INT32_MAX))
    check_agreement: bool = False
    check_false_suspicion: bool = False
    join_known_by: jnp.ndarray = dataclasses.field(
        default_factory=lambda: jnp.int32(INT32_MAX))
    check_joins: bool = False

    @staticmethod
    def passive(params: "swim.SwimParams") -> "MonitorSpec":
        """Safety-only spec: monotone incarnations, timer bounds and
        wire saturation checked; no scenario-derived liveness claims."""
        return MonitorSpec(
            complete_by=jnp.full((params.n_subjects,), INT32_MAX,
                                 dtype=jnp.int32),
            check_false_suspicion=False,
        )


jax.tree_util.register_dataclass(
    MonitorSpec,
    data_fields=["complete_by", "agree_from", "join_known_by"],
    meta_fields=["check_agreement", "check_false_suspicion",
                 "check_joins"],
)


# --------------------------------------------------------------------------
# Per-round checking (called inside the scan body)
# --------------------------------------------------------------------------


def _record_flat(mon: MonitorState, mask, rows) -> MonitorState:
    """Compact masked evidence rows into the lane buffer — the
    telemetry/trace.record_events_batch shape: cumsum slot assignment,
    ONE scatter, overflow counted (``cap`` index = drop)."""
    cap = mon.capacity
    slot = mon.count + jnp.cumsum(mask.astype(jnp.int32)) - 1
    idx = jnp.where(mask & (slot < cap), slot, cap)
    lanes = mon.lanes.at[idx].set(rows, mode="drop")
    total = jnp.sum(mask, dtype=jnp.int32)
    new_count = jnp.minimum(mon.count + total, cap)
    dropped = mon.dropped + total - (new_count - mon.count)
    return dataclasses.replace(mon, lanes=lanes, count=new_count,
                               dropped=dropped)


def _check_cells(spec: MonitorSpec, params: "swim.SwimParams",
                 kn: "swim.Knobs", round_idx, prev: "swim.SwimState",
                 new: "swim.SwimState", world: "swim.SwimWorld",
                 alive_now=None):
    """Evaluate every invariant on one tick's (prev, new) WIDE carries —
    the pure mask/total computation, shared by the sequential
    ``check_round`` and the batched scan (``run_monitored_batch``,
    which needs the masks separately so its evidence-recording
    ``lax.cond`` can gate on a BATCH-level predicate).

    ``alive_now``: the precomputed ``world.alive_at(round_idx)`` from
    the composed runner's shared round context
    (models/compose.RoundCtx); None recomputes it (identical bits).

    Returns ``(vio [N_CODES, N, K] bool, details [N_CODES, N, K] i32,
    v_self_inc [N] bool, v_self_sat [N] bool, self_inc [N] i32,
    totals [N_CODES] i32)``.
    """
    n, k = prev.status.shape
    node_ids = jnp.arange(n, dtype=jnp.int32)
    subject_ids = jnp.asarray(world.subject_ids, jnp.int32)
    if alive_now is None:
        alive_now = world.alive_at(round_idx)
    obs_alive = alive_now[:, None]
    subj_alive = alive_now[subject_ids][None, :]
    is_self = subject_ids[None, :] == node_ids[:, None]

    ps = prev.status
    pi = prev.inc.astype(jnp.int32)
    ns = new.status
    ni = new.inc.astype(jnp.int32)
    dl = new.suspect_deadline
    sat = jnp.int32(swim._wire_inc_sat(params))

    zero = jnp.zeros((n, k), dtype=jnp.bool_)

    # Open-world identity lane (zero-size when the plane is off — the
    # guard arm carries it, the naive control arm does not): the wide
    # epoch matrices and the slots' ground-truth epochs.  Separately,
    # the rows whose JOIN fires this round (a join schedule exists with
    # or without the lane): their reset legitimately rewinds
    # incarnations/epochs — exempt from the monotonicity checks, like
    # every other world-scheduled rebirth.
    has_epoch = new.epoch.size > 0
    if has_epoch:
        ne_ep = new.epoch.astype(jnp.int32)
        pe_ep = prev.epoch.astype(jnp.int32)
        true_ep = world.epoch_at(round_idx)[subject_ids][None, :]
    if world.join_at is not None:
        joining_row = (world.join_at[node_ids] == round_idx)[:, None]
        joining_vec = world.join_at[node_ids] == round_idx
    else:
        joining_row = zero
        joining_vec = jnp.zeros((n,), dtype=jnp.bool_)

    # FALSE_SUSPICION — new SUSPECT onset about a live subject on a
    # pristine network (static flag: folds to the zero mask otherwise).
    # With the identity lane present, only suspicions OF THE CURRENT
    # identity count: a maturing suspicion of the slot's PREVIOUS (dead)
    # occupant is not false merely because a new member now occupies
    # the slot — the stale-identity codes below own that hazard.
    if spec.check_false_suspicion:
        v_fs = (obs_alive & subj_alive & ~is_self
                & (ns == records.SUSPECT) & (ps != records.SUSPECT))
        if has_epoch:
            v_fs = v_fs & (ne_ep == true_ep)
    else:
        v_fs = zero

    # INC_REGRESSION — per-cell monotonicity over LIVE prior records.
    # A DEAD winner may legally carry a lower incarnation (isOverrides
    # case 3), an ABSENT cell has no prior, and a stored DEAD tombstone
    # gates like ABSENT (records.py storage convention) so the
    # delete-then-re-add path may re-accept ALIVE at any incarnation.
    # A cell whose identity EPOCH changed is a different member's
    # record — incarnations restart at 0 across identities — and a
    # joining observer's whole row is reborn: both exempt.  The NAIVE
    # arm (joins without the lane) additionally rewinds cells when a
    # new identity's inc-0 records overwrite the ghost's — exempt the
    # joined columns there; the join codes own that chaos.
    v_inc = (((ps == records.ALIVE) | (ps == records.SUSPECT))
             & (ns != records.DEAD) & (ni < pi)) & ~joining_row
    if has_epoch:
        v_inc = v_inc & (ne_ep == pe_ep)
    elif world.join_at is not None:
        v_inc = v_inc & ~(
            world.join_at[subject_ids] < INT32_MAX)[None, :]

    # TIMER_BOUND — live observers' suspicion-timer contract.  With the
    # Lifeguard plane on the deadline an observer may arm stretches to
    # suspicion_rounds * lhm_max (LHA Suspicion's ceiling —
    # models/lifeguard.suspicion_deadline_rounds); with the dead-member
    # suppression window on, a DEAD cell legitimately holds its
    # suppression expiry in the deadline lane (bounded by
    # dead_suppress_rounds).  Both features off reduces this to the
    # original contract exactly.
    susp = ns == records.SUSPECT
    has_timer = dl != INT32_MAX
    if params.dead_suppress_rounds > 0:
        dead_hold = (ns == records.DEAD) & has_timer
        v_dead_hold = dead_hold & (
            dl > round_idx + swim.knob_dead_suppress(kn, params))
    else:
        dead_hold = zero
        v_dead_hold = zero
    max_susp_rounds = kn.suspicion_rounds * max(1, params.lhm_max)
    v_timer = obs_alive & (
        (has_timer & ~susp & ~dead_hold)
        | (susp & ~has_timer)
        | (susp & has_timer & (dl <= round_idx))
        | (has_timer & ~dead_hold & (dl > round_idx + max_susp_rounds))
        | v_dead_hold
    )

    # WIRE_SATURATION — the carry must never exceed the wire cap.
    v_sat = (ni > sat) | (ni < 0)

    # COMPLETENESS — past the deadline, eligible observers must have
    # dropped the subject.  Eligible = continuously alive since the
    # subject's fault round: an observer whose own down window overlaps
    # [fault, now] legitimately re-learns by FD re-detection on its own
    # clock (SYNC never carries tombstones), so it is excluded.
    fault_ref = jnp.minimum(world.down_from, world.leave_at)[subject_ids]
    due = spec.complete_by[None, :] <= round_idx
    disturbed = ((world.down_from[:, None] <= round_idx)
                 & (world.down_until[:, None] > fault_ref[None, :]))
    v_comp = (due & obs_alive & ~disturbed & ~is_self
              & ((ns == records.ALIVE) | (ns == records.SUSPECT)))

    # POST_HEAL_DIVERGENCE — past the agreement deadline, every live
    # observer's (status, incarnation) record must equal the live
    # consensus (the column's max packed record among live observers —
    # models/sync.divergent_cells).  The SYNC anti-entropy plane's
    # bounded re-convergence contract; the static ``check_agreement``
    # flag folds the whole reduction to the zero mask when no promise
    # is made (the check_false_suspicion pattern).
    if spec.check_agreement:
        div_due = jnp.asarray(round_idx, jnp.int32) >= spec.agree_from
        div_cells, _ = msync.divergent_cells(ns, ni, alive_now)
        v_div = div_cells & div_due
    else:
        v_div = zero

    # NO_RESURRECTION / JOIN_COMPLETENESS — the open-world join codes
    # (module docstring).  Static ``check_joins`` folds both reductions
    # to the zero mask.
    #
    # NO_RESURRECTION has two detectors, both exactly zero in any
    # single-identity world:
    #   - incarnation forensics (attribution-free — the NAIVE arm's
    #     epoch-blind wire is precisely what it convicts): a live
    #     ALIVE/SUSPECT record carrying an incarnation ABOVE the
    #     subject's own current ``self_inc`` cannot describe the
    #     current occupant (records only ever carry the member's own
    #     announcements, which are <= self_inc and monotone within an
    #     identity) — it is a dead identity's record living in the
    #     table, counted from the instant the new identity exists.
    #     With the epoch lane present it applies to cells CLAIMING the
    #     current epoch (a guarded run's stale-epoch cells legitimately
    #     hold the old identity's numbers until the join disseminates);
    #     without the lane every live record claims the current
    #     occupant — naive reuse's sin — so it applies everywhere.
    #   - stale-epoch persistence (lane required): past the
    #     join-propagation deadline, a live observer still holds an
    #     ALIVE/SUSPECT record attributed to a dead epoch.
    #
    # JOIN_COMPLETENESS: past the deadline, an eligible observer
    # (continuously alive since the join — the COMPLETENESS
    # eligibility rule, which also excludes later joiners relearning
    # on their own clock) must hold the ground-truth-alive joined
    # member live — at its true epoch when the lane can say so.
    if spec.check_joins:
        live_rec = (ns == records.ALIVE) | (ns == records.SUSPECT)
        join_due = spec.join_known_by[None, :] <= round_idx
        joined_col = (world.join_at[subject_ids] < INT32_MAX)[None, :]
        subj_self_inc = new.self_inc[subject_ids][None, :]
        ghost_inc = (obs_alive & ~is_self & joined_col
                     & live_rec & (ni > subj_self_inc))
        if has_epoch:
            v_res = (ghost_inc & (ne_ep == true_ep)) | (
                join_due & obs_alive & ~is_self
                & live_rec & (ne_ep < true_ep)
            )
        else:
            v_res = ghost_inc
        disturbed_j = (
            (world.down_from[:, None] <= round_idx)
            & (world.down_until[:, None]
               > world.join_at[subject_ids][None, :])
        )
        known = live_rec & (ne_ep == true_ep) if has_epoch else live_rec
        v_jc = (join_due & joined_col & subj_alive & obs_alive
                & ~disturbed_j & ~is_self & ~known)
    else:
        v_res = zero
        v_jc = zero

    vio = jnp.stack([v_fs, v_inc, v_timer, v_sat, v_comp, v_div,
                     v_res, v_jc])
    ep_detail = ne_ep if has_epoch else ns.astype(jnp.int32)
    details = jnp.stack([ni, ni, jnp.where(has_timer, dl, -1), ni,
                         ns.astype(jnp.int32), ns.astype(jnp.int32),
                         ep_detail, ns.astype(jnp.int32)])

    # Self-incarnation lanes (subject == observer): regression + cap.
    # A joining node is REBORN at incarnation 0 — exempt.
    v_self_inc = (new.self_inc < prev.self_inc) & ~joining_vec    # [N]
    v_self_sat = new.self_inc > sat

    totals = jnp.sum(vio, axis=(1, 2), dtype=jnp.int32)
    totals = (totals
              .at[InvariantCode.INC_REGRESSION]
              .add(jnp.sum(v_self_inc, dtype=jnp.int32))
              .at[InvariantCode.WIRE_SATURATION]
              .add(jnp.sum(v_self_sat, dtype=jnp.int32)))
    return vio, details, v_self_inc, v_self_sat, new.self_inc, totals


def _record_round(mon: MonitorState, round_idx, vio, details, v_self_inc,
                  v_self_sat, self_inc, subject_ids,
                  fresh) -> MonitorState:
    """The evidence-recording pass for one round's ``_check_cells``
    output: first-trip lanes of every freshly tripped code compacted
    into the buffer (``_record_flat``).  A NO-OP when nothing fresh
    tripped (every mask cell is false), which is what lets callers run
    it under a ``lax.cond`` whose predicate covers a whole batch."""
    n = v_self_inc.shape[0]
    node_ids = jnp.arange(n, dtype=jnp.int32)
    cell_code_of = jnp.asarray([
        InvariantCode.FALSE_SUSPICION, InvariantCode.INC_REGRESSION,
        InvariantCode.TIMER_BOUND, InvariantCode.WIRE_SATURATION,
        InvariantCode.COMPLETENESS, InvariantCode.POST_HEAL_DIVERGENCE,
        InvariantCode.NO_RESURRECTION, InvariantCode.JOIN_COMPLETENESS,
    ], dtype=jnp.int32)
    cell_fresh = fresh[cell_code_of][:, None, None]
    obs_grid = jnp.broadcast_to(node_ids[None, :, None], vio.shape)
    subj_grid = jnp.broadcast_to(subject_ids[None, None, :], vio.shape)
    code_grid = jnp.broadcast_to(cell_code_of[:, None, None], vio.shape)
    mask = jnp.concatenate([
        (vio & cell_fresh).reshape(-1),
        v_self_inc & fresh[InvariantCode.INC_REGRESSION],
        v_self_sat & fresh[InvariantCode.WIRE_SATURATION],
    ])
    self_codes = (
        jnp.full((n,), InvariantCode.INC_REGRESSION, jnp.int32),
        jnp.full((n,), InvariantCode.WIRE_SATURATION, jnp.int32),
    )
    rows = jnp.stack([
        jnp.full(mask.shape, round_idx, dtype=jnp.int32),
        jnp.concatenate([obs_grid.reshape(-1), node_ids, node_ids]),
        jnp.concatenate([subj_grid.reshape(-1), node_ids, node_ids]),
        jnp.concatenate([code_grid.reshape(-1), *self_codes]),
        jnp.concatenate([details.reshape(-1), self_inc, self_inc]),
    ], axis=1)
    return _record_flat(mon, mask, rows)


def check_round(mon: MonitorState, spec: MonitorSpec,
                params: "swim.SwimParams", kn: "swim.Knobs", round_idx,
                prev: "swim.SwimState", new: "swim.SwimState",
                world: "swim.SwimWorld", alive_now=None) -> MonitorState:
    """Evaluate every invariant on one tick's (prev, new) WIDE carries
    (``_check_cells``) and fold the result into the monitor carry.

    Pure jnp, called inside the scan body; the whole evidence-recording
    pass runs under a ``lax.cond`` and is skipped unless a code trips
    for the first time, so green rounds cost a handful of fused
    elementwise reductions.
    """
    vio, details, v_self_inc, v_self_sat, self_inc, totals = _check_cells(
        spec, params, kn, round_idx, prev, new, world,
        alive_now=alive_now)
    subject_ids = jnp.asarray(world.subject_ids, jnp.int32)

    fresh = mon.code_counts == 0                          # [N_CODES]
    new_counts = mon.code_counts + totals
    first_round = jnp.where(
        fresh & (totals > 0), jnp.asarray(round_idx, jnp.int32),
        mon.code_first_round,
    )

    mon = jax.lax.cond(
        jnp.any(fresh & (totals > 0)),
        lambda m: _record_round(m, round_idx, vio, details, v_self_inc,
                                v_self_sat, self_inc, subject_ids, fresh),
        lambda m: m, mon,
    )
    return dataclasses.replace(mon, code_counts=new_counts,
                               code_first_round=first_round)


# --------------------------------------------------------------------------
# The monitored run
# --------------------------------------------------------------------------


def _wide(params: "swim.SwimParams", st: "swim.SwimState", cursor):
    """Any carry layout -> the WIDE form the checks read — the
    composed runner's one decode site (models/compose.wide_view),
    re-exported under the historical name for the batched fuzzer."""
    from scalecube_cluster_tpu.models import compose

    return compose.wide_view(params, st, cursor)


class MonitorPlane:
    """The in-jit invariant monitor as a composed-runner plane
    (models/compose.py): carry slice = :class:`MonitorState`, per-round
    hook = :func:`check_round` on the shared round context's wide
    decodes (``rc.prev_wide``/``rc.new_wide`` — computed once and
    shared with every other plane in the stack), no finalizer work.

    ``monitor`` resumes an existing buffer across chunked scans (the
    ``run_monitored(monitor=...)`` argument threads through here).
    The slice is NOT donated by any entry point — chaos runs are
    small-N adversarial workloads, not the 1M hot path.
    """

    name = "monitor"

    def __init__(self, spec: MonitorSpec, capacity: int = DEFAULT_CAPACITY,
                 monitor: Optional[MonitorState] = None):
        self.spec = spec
        self.capacity = capacity
        self.monitor = monitor

    def init(self, params, world):
        if self.monitor is not None:
            return self.monitor
        return MonitorState.init(self.capacity)

    def on_round(self, rc, mon):
        return check_round(mon, self.spec, rc.params, rc.kn, rc.round_idx,
                           rc.prev_wide, rc.new_wide, rc.world,
                           alive_now=rc.alive_now)

    def on_round_batch(self, rc, mon):
        """The batched fold (models/compose.composed_batch_scan):
        ``self.spec`` and the ctx lanes carry a leading batch axis;
        the checks vmap per row, but the evidence-recording pass keeps
        ONE ``lax.cond`` gated on the whole batch's fresh-trip
        predicate — any row freshly tripping any code.  For rows with
        nothing fresh ``_record_round`` is an exact no-op, so the
        batch-level gate records the same per-row lanes the sequential
        path records (verdict parity pinned by tests/test_chaos_fuzz.py
        and tests/test_compose_batch.py).
        """
        cells = jax.vmap(
            lambda spec, kn, prev, new, world, alive: _check_cells(
                spec, rc.params, kn, rc.round_idx, prev, new, world,
                alive_now=alive)
        )(self.spec, rc.kn, rc.prev_wide, rc.new_wide, rc.world,
          rc.alive_now)
        vio, details, v_self_inc, v_self_sat, self_inc, totals = cells
        fresh = mon.code_counts == 0            # [B, N_CODES]
        trip = fresh & (totals > 0)
        subj = jnp.asarray(rc.world.subject_ids, jnp.int32)
        mon = jax.lax.cond(
            jnp.any(trip),
            lambda m: jax.vmap(
                _record_round,
                in_axes=(0, None, 0, 0, 0, 0, 0, 0, 0),
            )(m, rc.round_idx, vio, details, v_self_inc, v_self_sat,
              self_inc, subj, fresh),
            lambda m: m, mon,
        )
        return dataclasses.replace(
            mon,
            code_counts=mon.code_counts + totals,
            code_first_round=jnp.where(
                trip, jnp.asarray(rc.round_idx, jnp.int32),
                mon.code_first_round),
        )

    def finalize(self, fc, mon):
        return mon


@partial(jax.jit, static_argnames=("params", "n_rounds", "capacity"))
def run_monitored(base_key, params: "swim.SwimParams",
                  world: "swim.SwimWorld", spec: MonitorSpec,
                  n_rounds: int, capacity: int = DEFAULT_CAPACITY,
                  state: Optional["swim.SwimState"] = None,
                  start_round: int = 0,
                  knobs: Optional["swim.Knobs"] = None, shift_key=None,
                  monitor: Optional[MonitorState] = None):
    """``models/swim.run`` with the invariant monitor carried through
    the scan.

    Returns ``(final_state, monitor_state, metrics)``.  The monitor
    only OBSERVES: protocol state and metrics are bit-identical to
    ``swim.run`` on the same arguments, and a violated run completes
    normally — the verdict lives in the returned
    :class:`MonitorState` (graceful degradation).  ``monitor`` resumes
    an existing buffer across chunked scans, like ``run_traced``'s
    ``telemetry`` argument (the carry is NOT donated — chaos runs are
    small-N adversarial workloads, not the 1M hot path).

    Works on every carry layout: compact/int16 carries are decoded to
    the wide form for checking only (``swim._carry_decode`` — lossless
    below the caps the layouts already validate).

    Thin alias over the composed plane runner
    (models/compose.composed_scan with a single :class:`MonitorPlane`);
    the scan body lives there.
    """
    from scalecube_cluster_tpu.models import compose

    plane = MonitorPlane(spec, capacity=capacity, monitor=monitor)
    final_state, results, metrics = compose.composed_scan(
        base_key, params, world, n_rounds, planes=(plane,), state=state,
        start_round=start_round, knobs=knobs, shift_key=shift_key,
    )
    return final_state, results["monitor"], metrics


@partial(jax.jit, static_argnames=("params", "n_rounds", "capacity"))
def run_monitored_batch(base_keys, params: "swim.SwimParams", worlds,
                        specs, n_rounds: int,
                        capacity: int = DEFAULT_CAPACITY, knobs=None):
    """ONE device program fuzzing a whole scenario batch: the monitored
    scan with every per-round computation ``jax.vmap``-ed over a
    leading batch axis of (PRNG key, world, spec-dynamic lanes[,
    knobs]).

    The batch must share ONE compiled shape signature — same ``params``
    (static), same horizon, same world/spec pytree shapes; that is
    exactly what the scenario generator's compile hygiene (quantized
    horizons, padded rule widths — chaos/scenarios.py) buys, and what
    ``chaos.campaign.build_buckets`` groups by.  The batched ``specs``
    may differ only in DATA lanes (deadlines); the static treedef flags
    (``check_false_suspicion`` etc.) are shared by construction.

    The scan stays OUTSIDE the vmap so the evidence-recording pass can
    keep its ``lax.cond`` with a predicate reduced over the WHOLE batch
    (any row freshly tripping any code): under a per-row vmap the cond
    would degrade to running the recording branch every round for every
    row — measured 4-5x slower than the sequential loop it is supposed
    to beat — while ``_record_round`` is a no-op for rows with nothing
    fresh, so gating on the batch-level predicate records the exact
    per-row lanes the sequential path records.

    ``knobs`` (optional, batched like the keys) are the per-row dynamic
    protocol knobs; None uses ``Knobs.from_params`` broadcast over the
    batch.  Because knobs are traced DATA, a rerun of the same batch
    with different knobs — the deliberately-weakened coverage arm
    (``chaos.campaign.weakened_knobs``) — reuses this function's
    compiled program.

    Returns ``(final_states, monitors, metrics)``, each with a leading
    batch axis; row i is exactly what ``run_monitored(base_keys[i],
    params, world_i, spec_i, n_rounds, capacity)`` would have produced
    (verdict parity pinned by tests/test_chaos_fuzz.py).

    Thin alias over the batched composed runner
    (models/compose.composed_batch_scan with a single
    :class:`MonitorPlane` whose ``on_round_batch`` carries the
    batch-level evidence cond); the scan body lives there.
    """
    from scalecube_cluster_tpu.models import compose

    plane = MonitorPlane(specs, capacity=capacity)
    final_states, results, metrics = compose.composed_batch_scan(
        base_keys, params, worlds, n_rounds, planes=(plane,),
        knobs=knobs,
    )
    return final_states, results["monitor"], metrics


def unstack_monitor(mon: MonitorState) -> List[MonitorState]:
    """Split a batched (leading-axis) :class:`MonitorState` — the
    ``run_monitored_batch`` output — into per-row host-side states, each
    of which decodes/verdicts exactly like a sequentially produced one
    (``decode_violations`` / ``verdict``)."""
    arrays = {f.name: np.asarray(getattr(mon, f.name))
              for f in dataclasses.fields(MonitorState)}
    batch = arrays["count"].shape[0]
    return [MonitorState(**{k: v[i] for k, v in arrays.items()})
            for i in range(batch)]


@partial(jax.jit, static_argnames=("params", "n_rounds", "capacity",
                                   "metrics_spec"),
         donate_argnames=("metrics_state",))
def run_monitored_metered(base_key, params: "swim.SwimParams",
                          world: "swim.SwimWorld", spec: MonitorSpec,
                          n_rounds: int,
                          capacity: int = DEFAULT_CAPACITY,
                          state: Optional["swim.SwimState"] = None,
                          start_round: int = 0,
                          knobs: Optional["swim.Knobs"] = None,
                          shift_key=None,
                          monitor: Optional[MonitorState] = None,
                          metrics_spec=None, metrics_state=None):
    """``run_monitored`` with the health-metrics registry riding along
    (telemetry/metrics.py): the chaos shape of the always-on numeric
    health plane — the same composed scan with a
    :class:`~telemetry.metrics.MetricsPlane` stacked after the
    :class:`MonitorPlane` (its ``chaos_from`` hook feeds the
    ``chaos_violations`` counter from the monitor's per-round count
    delta), so monitor verdicts and protocol state are bit-identical
    to ``run_monitored``.

    Returns ``(final_state, monitor_state, metrics_state, metrics)``;
    ``metrics_state``/``metrics_spec`` resume/declare the registry like
    ``swim.run_metered`` (the registry carry is donated; the monitor
    carry is not, matching ``run_monitored``).

    Thin alias over models/compose.composed_scan; the scan body lives
    there.
    """
    from scalecube_cluster_tpu.models import compose
    from scalecube_cluster_tpu.telemetry import metrics as tmetrics

    if metrics_spec is None:
        metrics_spec = tmetrics.MetricsSpec.default()
    planes = (
        MonitorPlane(spec, capacity=capacity, monitor=monitor),
        tmetrics.MetricsPlane(metrics_spec, metrics_state=metrics_state,
                              chaos_from="monitor"),
    )
    final_state, results, metrics = compose.composed_scan(
        base_key, params, world, n_rounds, planes=planes, state=state,
        start_round=start_round, knobs=knobs, shift_key=shift_key,
    )
    return final_state, results["monitor"], results["metrics"], metrics


# --------------------------------------------------------------------------
# Host-side decoding + verdicts
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True, order=True)
class InvariantViolation:
    """One recorded first-violation evidence lane."""

    round: int
    observer: int
    subject: int
    code: InvariantCode
    detail: int

    def to_json(self) -> dict:
        return {
            "round": self.round,
            "observer": self.observer,
            "subject": self.subject,
            "code": self.code.name,
            "detail": self.detail,
        }


def decode_violations(mon: MonitorState) -> List[InvariantViolation]:
    """Device buffer -> typed evidence list (host side; exact recorded
    prefix, ``mon.dropped`` counts what the capacity cut off)."""
    lanes = np.asarray(mon.lanes)
    return [
        InvariantViolation(
            round=int(lanes[i, 0]),
            observer=int(lanes[i, 1]),
            subject=int(lanes[i, 2]),
            code=InvariantCode(int(lanes[i, 3])),
            detail=int(lanes[i, 4]),
        )
        for i in range(int(mon.count))
    ]


def verdict(mon: MonitorState, max_evidence: int = 32) -> dict:
    """Host-side verdict digest: green flag, per-code totals and first
    rounds, and up to ``max_evidence`` decoded evidence lanes —
    the JSONL-manifest-ready form."""
    counts = np.asarray(mon.code_counts)
    firsts = np.asarray(mon.code_first_round)
    codes = {
        InvariantCode(c).name: {
            "violations": int(counts[c]),
            "first_round": (int(firsts[c]) if firsts[c] != INT32_MAX
                            else None),
        }
        for c in range(N_CODES)
    }
    return {
        "green": bool(counts.sum() == 0),
        "total_violations": int(counts.sum()),
        "codes": codes,
        "evidence_recorded": int(mon.count),
        "evidence_dropped": int(mon.dropped),
        "evidence": [v.to_json()
                     for v in decode_violations(mon)[:max_evidence]],
    }
