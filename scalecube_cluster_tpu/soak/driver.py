"""The soak driver: one long-lived composed run under streaming chaos.

``run_soak`` wires the pieces the prior PRs built into one service
lifetime:

- the schedule streamer (soak/schedule.py) materializes the seeded
  chaos stream into one Scenario + MonitorSpec (one compile for every
  segment);
- the resilient supervisor's ``composed`` shape runs the FULL plane
  stack (trace ⊕ monitor ⊕ metrics, with SYNC / Lifeguard /
  open-world armed on the params) in checkpointed segments, streaming
  ``segment`` / ``metrics_window`` / ``alarm_transition`` rows to one
  JSONL journal with the exactly-once resume guarantee;
- per-segment **drift invariants** sample the host side through the
  supervisor's ``on_segment`` hook: the compose program's compile
  cache must stay FLAT after the first executed segment (the PR-14
  compile-cache audit as a runtime soak invariant), host RSS must stay
  bounded, and the monitor must end green.

Drift samples stay OUT of the journal (RSS is nondeterministic; cache
size is process-local) — the journal remains byte-reproducible, which
is what :func:`kill_resume_drill` asserts: SIGKILL a soak mid-flight,
relaunch, and the merged journal's content rows (``segment`` /
``metrics_window`` / ``alarm_transition``) are byte-identical to an
uninterrupted reference run's, with a bit-identical final state
digest.  ``manifest``/``resume``/``summary`` rows are process metadata
(wall-clock, relaunch provenance) and are excluded by definition.

Subprocess child entry::

    python -m scalecube_cluster_tpu.soak.driver --config soak.json

prints one JSON summary line (state digest + drift verdict) — the
resilience-harness child contract.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
from typing import List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: Journal record kinds that are CONTENT (deterministic protocol
#: output, byte-reproducible across kill/relaunch) as opposed to
#: process metadata (manifest wall-time, resume provenance, summary).
CONTENT_KINDS = ("segment", "metrics_window", "alarm_transition")

DEFAULT_RSS_LIMIT_MB = 512.0


@dataclasses.dataclass(frozen=True)
class SoakConfig:
    """One soak run, JSON-serializable (the subprocess config unit)."""

    base_path: str
    seed: int = 7
    n_members: int = 32
    severity: str = "moderate"
    segment_rounds: int = 128
    n_segments: int = 4
    delivery: str = "shift"
    lhm_max: int = 2
    keep_generations: int = 3
    rss_limit_mb: float = DEFAULT_RSS_LIMIT_MB

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_json(obj: dict) -> "SoakConfig":
        return SoakConfig(**obj)

    @property
    def n_rounds(self) -> int:
        return self.n_segments * self.segment_rounds

    @property
    def journal_path(self) -> str:
        return f"{self.base_path}.journal.jsonl"


def build_workload(cfg: SoakConfig):
    """(key, params, world, spec, scenario) for one soak config: the
    stream's scenario compiled against the campaign timing preset with
    the Lifeguard plane armed (``lhm_max``) and open-world on whenever
    the stream schedules joins (campaign_params does that part)."""
    import jax

    from scalecube_cluster_tpu.chaos import campaign as cc
    from scalecube_cluster_tpu.soak import schedule as sched

    scenario = sched.soak_schedule(
        cfg.seed, cfg.n_segments, n=cfg.n_members,
        severity=cfg.severity, segment_rounds=cfg.segment_rounds)
    params = cc.campaign_params(scenario, delivery=cfg.delivery,
                                lhm_max=cfg.lhm_max)
    world, spec = scenario.build(params)
    return jax.random.key(cfg.seed), params, world, spec, scenario


# --------------------------------------------------------------------------
# The soak run
# --------------------------------------------------------------------------


@dataclasses.dataclass
class SoakResult:
    """run_soak's host-side return: the supervisor result plus the
    drift verdict and the journal's alarm summary."""

    result: object            # supervisor.ResilientRunResult
    drift: dict
    alarms: dict
    scenario_name: str
    rounds: int
    segments: int


def run_soak(cfg: SoakConfig, kill_plan=None, alarm_specs=None,
             log=None) -> SoakResult:
    """One soak lifetime (or one relaunch of it — resume is the
    supervisor's job).  ``alarm_specs`` default to the live FP-rate
    alarm (telemetry/alarms.default_specs); pass ``()`` to disarm."""
    from scalecube_cluster_tpu.resilience import store as rstore
    from scalecube_cluster_tpu.resilience import supervisor as rsup
    from scalecube_cluster_tpu.soak import drift as sdrift
    from scalecube_cluster_tpu.telemetry import alarms as talarms
    from scalecube_cluster_tpu.telemetry import sink as tsink

    if alarm_specs is None:
        alarm_specs = talarms.default_specs()
    key, params, world, spec, scenario = build_workload(cfg)
    store = rstore.CheckpointStore(cfg.base_path,
                                   keep=cfg.keep_generations)

    samples: List[dict] = []

    def sample(record: dict) -> None:
        # Late-bound through the module so the drift-trip test can
        # monkeypatch soak.drift.cache_size_probe mid-run.
        samples.append({
            "round_end": int(record["round_end"]),
            "cache_size": sdrift.cache_size_probe(),
            "rss_kb": sdrift.rss_kb(),
        })

    result = rsup.run_resilient(
        rsup.RunShape.COMPOSED, key, params, world, cfg.n_rounds,
        store=store, segment_rounds=cfg.segment_rounds,
        journal_path=cfg.journal_path, spec=spec,
        alarm_specs=alarm_specs, kill_plan=kill_plan,
        on_segment=sample, log=log,
        meta={"workload": "soak", "scenario": scenario.name,
              "severity": cfg.severity, "seed": cfg.seed},
    )

    transitions = tsink.read_records(cfg.journal_path,
                                     kind=talarms.TRANSITION_KIND)
    firing = sum(1 for t in transitions if t.get("to") == "firing")
    alarms = {
        "specs": [s.name for s in alarm_specs],
        "transitions": len(transitions),
        "firing": firing,
        "quiet": len(transitions) == 0,
    }
    drift = sdrift.drift_verdict(samples, cfg.rss_limit_mb,
                                 result.monitor_verdict)
    return SoakResult(
        result=result, drift=drift, alarms=alarms,
        scenario_name=scenario.name, rounds=cfg.n_rounds,
        segments=cfg.n_segments,
    )


def result_digest(result) -> str:
    """Content digest of the full final carry (state + every plane
    aux lane) — the bit-identity the kill drill asserts."""
    from scalecube_cluster_tpu.resilience import store as rstore

    return rstore.payload_checksum(result.result.carry_arrays)


# --------------------------------------------------------------------------
# Journal identity + the kill/resume drill
# --------------------------------------------------------------------------


def content_rows(path: str) -> List[bytes]:
    """The journal's CONTENT rows as raw byte lines, in file order —
    the byte-identity unit of the kill drill (module docstring).  Only
    newline-terminated lines count (the durability rule); a torn tail
    is skipped like read_records does."""
    out: List[bytes] = []
    with open(path, "rb") as f:
        data = f.read()
    for raw in data.split(b"\n")[:-1]:
        if not raw.strip():
            continue
        try:
            kind = json.loads(raw).get("kind")
        except json.JSONDecodeError:
            continue   # torn mid-journal kill fragment, reader-skipped
        if kind in CONTENT_KINDS:
            out.append(raw)
    return out


def _child_env(extra_env: Optional[dict] = None) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (_REPO_ROOT, env.get("PYTHONPATH")) if p)
    env.update(extra_env or {})
    return env


def launch_child(cfg: SoakConfig, cfg_path: str, kill_plan=None,
                 timeout: float = 600.0,
                 extra_env: Optional[dict] = None):
    """One soak child launch (the resilience-harness subprocess
    contract: kill plan rides SCALECUBE_RESILIENCE_KILL, paths
    absolutized, cwd pinned to the repo root)."""
    from scalecube_cluster_tpu.resilience import supervisor as rsup

    cfg = dataclasses.replace(
        cfg, base_path=os.path.abspath(cfg.base_path))
    with open(cfg_path, "w") as f:
        json.dump(cfg.to_json(), f)
    env = _child_env(extra_env)
    if kill_plan is not None:
        env[rsup.KILL_ENV] = kill_plan.encode()
    else:
        env.pop(rsup.KILL_ENV, None)
    return subprocess.run(
        [sys.executable, "-m", "scalecube_cluster_tpu.soak.driver",
         "--config", cfg_path],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=_REPO_ROOT,
    )


def kill_resume_drill(cfg: SoakConfig, workdir: str,
                      kill_round: Optional[int] = None,
                      stage: str = "post_journal",
                      timeout: float = 600.0,
                      extra_env: Optional[dict] = None) -> dict:
    """SIGKILL one soak mid-flight, relaunch it to completion, and
    compare against an uninterrupted reference run in its own lineage:
    the merged journal's content rows must be BYTE-identical and the
    final carry digest bit-identical (both children share env, so the
    comparison never crosses backends)."""
    from scalecube_cluster_tpu.resilience import harness as rharness
    from scalecube_cluster_tpu.resilience import supervisor as rsup

    os.makedirs(workdir, exist_ok=True)
    if kill_round is None:
        kill_round = (cfg.n_segments // 2) * cfg.segment_rounds or \
            cfg.segment_rounds

    ref_cfg = dataclasses.replace(
        cfg, base_path=os.path.join(workdir, "ref", "soak.ckpt"))
    os.makedirs(os.path.dirname(ref_cfg.base_path), exist_ok=True)
    ref = launch_child(ref_cfg, os.path.join(workdir, "ref_config.json"),
                       timeout=timeout, extra_env=extra_env)
    if ref.returncode != 0:
        return {"ok": False, "error": "reference soak failed",
                "stderr_tail": ref.stderr[-2000:]}
    ref_summary = json.loads(
        [ln for ln in ref.stdout.strip().splitlines() if ln][-1])

    killed_cfg = dataclasses.replace(
        cfg, base_path=os.path.join(workdir, "killed", "soak.ckpt"))
    os.makedirs(os.path.dirname(killed_cfg.base_path), exist_ok=True)
    cfg_path = os.path.join(workdir, "killed_config.json")
    plan = rsup.KillPlan(round=kill_round, stage=stage)
    killed = launch_child(killed_cfg, cfg_path, kill_plan=plan,
                          timeout=timeout, extra_env=extra_env)
    if killed.returncode != -signal.SIGKILL:
        return {"ok": False, "error": "kill did not land",
                "returncode": killed.returncode,
                "stderr_tail": killed.stderr[-2000:]}
    relaunch = launch_child(killed_cfg, cfg_path, timeout=timeout,
                            extra_env=extra_env)
    if relaunch.returncode != 0:
        return {"ok": False, "error": "relaunch failed",
                "stderr_tail": relaunch.stderr[-2000:]}
    summary = json.loads(
        [ln for ln in relaunch.stdout.strip().splitlines() if ln][-1])

    ref_rows = content_rows(ref_summary["journal"])
    got_rows = content_rows(summary["journal"])
    journal_match = got_rows == ref_rows
    state_match = summary["state_digest"] == ref_summary["state_digest"]
    coverage = rharness.verify_journal(summary["journal"], cfg.n_rounds)
    return {
        "ok": bool(journal_match and state_match
                   and coverage["complete"]),
        "kill": plan.encode(),
        "journal_match": journal_match,
        "state_match": state_match,
        "journal_complete": coverage["complete"],
        "journal_problems": coverage["problems"],
        "content_rows": len(got_rows),
        "resumed_segments": summary["segments_run"],
        "state_digest": summary["state_digest"],
        "ref_digest": ref_summary["state_digest"],
        "ref_summary": ref_summary,
    }


# --------------------------------------------------------------------------
# Child mode
# --------------------------------------------------------------------------


def child_main(argv=None) -> int:
    """Run one soak to completion (the subprocess body): arm the kill
    plan from the env, print one JSON summary line."""
    import argparse

    parser = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    parser.add_argument("--config", required=True,
                        help="path to a SoakConfig JSON file")
    args = parser.parse_args(argv)
    with open(args.config) as f:
        cfg = SoakConfig.from_json(json.load(f))

    from scalecube_cluster_tpu.resilience import supervisor as rsup
    from scalecube_cluster_tpu.utils import runlog

    runlog.enable_compilation_cache()
    soak = run_soak(cfg, kill_plan=rsup.KillPlan.from_env())
    print(json.dumps({
        "state_digest": result_digest(soak),
        "journal": soak.result.journal_path,
        "rounds": soak.rounds,
        "segments_run": soak.result.segments_run,
        "segments_deduped": soak.result.segments_deduped,
        "resumed": soak.result.resumed_from is not None,
        "drift": soak.drift,
        "alarms": soak.alarms,
        "scenario": soak.scenario_name,
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(child_main())
