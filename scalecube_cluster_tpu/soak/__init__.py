"""Production soak mode: one long-lived service under continuous
streaming chaos, with kill/resume and drift invariants.

- ``soak/schedule.py``  the never-repeating seeded chaos stream: an
  open-horizon sequence of per-segment scenario slices, pure in
  ``(seed, segment_index, n, severity)``, every segment boundary
  straddled by an in-flight fault so a kill never lands on a clean
  edge.
- ``soak/drift.py``     host-side drift probes (compose compile-cache
  size, RSS) + the per-segment invariant verdict — sampled, never
  journaled, so the journal stays byte-reproducible.
- ``soak/driver.py``    ``run_soak``: the full plane stack
  (trace ⊕ metrics ⊕ monitor ⊕ sync ⊕ lifeguard ⊕ open-world) through
  the resilient supervisor's ``composed`` shape, streaming
  segment/metrics_window/alarm_transition rows to one JSONL journal,
  with per-segment drift invariants (flat compile cache, bounded RSS,
  zero monitor violations) and a SIGKILL/relaunch drill whose merged
  journal is byte-identical to an uninterrupted reference run.

``bench.py --soak [--smoke]`` is the measured entry
(``artifacts/soak_report.json``); ``experiments/soak.py`` the
repro driver.
"""

from scalecube_cluster_tpu.soak.schedule import (  # noqa: F401
    SoakSegment,
    soak_schedule,
    soak_segment,
)
