"""The soak schedule streamer: a never-repeating seeded chaos stream.

``chaos/scenarios.generate_scenario`` draws ONE bounded scenario from
``(seed, n, severity)``; this module extends the same idiom to an
open-horizon *stream* of per-segment scenario slices:

- :func:`soak_segment` is PURE in ``(seed, segment_index, n, severity,
  segment_rounds)`` — segment 400 is computable without materializing
  segments 0..399, so any slice of a soak's lifetime is its own
  one-line repro (the campaign purity contract, streamed).
- Draw order follows the PR-10/PR-12 **trailing-draw contract**: the
  boundary straddler is drawn first, then the severity-tier interior
  ops, then the trailing rungs (the net-zero join storm, then the
  rolling metadata config push) — future tiers must APPEND draws
  after the existing ones, never reshuffle them
  (tests/test_soak.py pins the historical (seed, segment) → op-kind
  table exactly like the generate_scenario pin in
  tests/test_chaos_fuzz.py).
- Every segment's FIRST draw is an op that *straddles* the segment's
  trailing edge (a crash whose revive lands in the next segment, a
  flapping link mid-cycle across the boundary, a loss window spanning
  it), so fault state — open partitions, suspicion in flight, pending
  joins — is always live at a segment boundary and a checkpoint/kill
  never lands on a "clean" edge.

Node-schedule ops (crash/burst/churn) get ONE down window per node in
``SwimWorld`` (``with_crash`` overwrites — the leave-clobbers-crash
composition edge), so the stream partitions the node space: a global
severity-seeded permutation hands each segment a disjoint quota, a
quorum reserve is never node-faulted, and segments past the quota
degrade to link-level weather (flaps, brownouts, loss windows — the
``LinkFaults`` rule list appends without bound).  The trailing
open-world rung is a NET-ZERO join storm: permanent crashes whose
slots are re-admitted as fresh identities ``join_lag`` rounds later —
slot occupancy returns to full, so the stream never exhausts the
cluster.

:func:`soak_schedule` concatenates segments ``[0, n_segments)`` into
one :class:`chaos.scenarios.Scenario` (horizon =
``n_segments * segment_rounds``) that compiles through the existing
``Scenario.build`` path — one world, one MonitorSpec, one XLA program
for every segment of the soak.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from scalecube_cluster_tpu.chaos import scenarios as cs

# Seed-stream namespace: decorrelates the soak stream from
# generate_scenario's [seed, severity] SeedSequence space so soaking
# seed 7 and campaigning seed 7 never share draws.
_STREAM_DOMAIN = 18

#: Minimum segment length: draws need room for a revive window plus
#: the boundary straddler, and the horizon quantum keeps compiled
#: shapes shared (chaos/scenarios._HORIZON_QUANTUM).
MIN_SEGMENT_ROUNDS = 2 * cs._HORIZON_QUANTUM
DEFAULT_SEGMENT_ROUNDS = 256

#: Per-segment node-fault quota by severity (disjoint slices of the
#: global permutation — module docstring).
_NODE_QUOTA = {"mild": 2, "moderate": 6, "severe": 8}

#: Background symmetric wire loss per severity (the generate_scenario
#: tiers, pinned to one value per tier so the whole stream shares one
#: params — and therefore one compile).
_STREAM_LOSS = {"mild": 0.0, "moderate": 0.02, "severe": 0.05}


@dataclasses.dataclass(frozen=True)
class SoakSegment:
    """One slice of the stream: ops carry GLOBAL round numbers
    (``round_start`` + local draw), ``kinds`` the draw-order op-kind
    names (the seed-stability pin unit), ``spans_boundary`` that the
    first op straddles ``round_end`` (True by construction — asserted,
    not assumed, by tests/test_soak.py)."""

    index: int
    round_start: int
    round_end: int
    kinds: Tuple[str, ...]
    ops: Tuple[object, ...]
    spans_boundary: bool


def _stream_permutation(seed: int, n: int, severity: str):
    """The stream-global node permutation (pure in (seed, n,
    severity); segment-independent so every segment can compute its
    own disjoint slice).  The first ``n - n // 4`` entries are the
    faultable pool; the tail quarter is the quorum reserve."""
    rng = np.random.default_rng(np.random.SeedSequence(
        [seed, _STREAM_DOMAIN, cs.SEVERITIES.index(severity)]))
    return [int(x) for x in rng.permutation(n)]


def _fault_pool(seed: int, n: int, severity: str):
    """The faultable-node slice: a quarter of the cluster is a quorum
    reserve that never takes a node-schedule fault."""
    return _stream_permutation(seed, n, severity)[:n - n // 4]


def _config_owner_ring(seed: int, n: int, severity: str):
    """The quorum reserve in permutation order: the rolling ConfigPush
    owner ring.  Disjoint from :func:`_fault_pool` by construction, so
    a push owner is never node-down when its push lands — the
    metadata-under-churn question the soak asks is about *propagation*
    through the weather, not about injecting into a crashed slot."""
    return _stream_permutation(seed, n, severity)[n - n // 4:]


def soak_segment(seed: int, segment_index: int, n: int = 32,
                 severity: str = "moderate",
                 segment_rounds: int = DEFAULT_SEGMENT_ROUNDS,
                 params=None) -> SoakSegment:
    """Segment ``segment_index`` of the stream — pure in every
    argument (module docstring).  ``params`` only shapes the revive /
    join-lag arithmetic (defaults to the campaign timing preset at n,
    exactly like generate_scenario)."""
    if severity not in cs.SEVERITIES:
        raise ValueError(f"unknown severity {severity!r} "
                         f"(choose from {cs.SEVERITIES})")
    if n < 16:
        raise ValueError(f"soak streams need n >= 16 (got {n})")
    if segment_index < 0:
        raise ValueError(f"segment_index must be >= 0, "
                         f"got {segment_index}")
    if (segment_rounds < MIN_SEGMENT_ROUNDS
            or segment_rounds % cs._HORIZON_QUANTUM):
        raise ValueError(
            f"segment_rounds must be a multiple of "
            f"{cs._HORIZON_QUANTUM} and >= {MIN_SEGMENT_ROUNDS}, "
            f"got {segment_rounds}")
    if params is None:
        from scalecube_cluster_tpu.chaos.campaign import campaign_config
        from scalecube_cluster_tpu.models import swim

        params = swim.SwimParams.from_config(campaign_config(),
                                             n_members=n)
    rng = np.random.default_rng(np.random.SeedSequence(
        [seed, _STREAM_DOMAIN, cs.SEVERITIES.index(severity),
         segment_index]))
    start = segment_index * segment_rounds
    end = start + segment_rounds
    revive_down = int(2 * params.suspicion_rounds + 24)

    quota = _NODE_QUOTA[severity]
    pool = _fault_pool(seed, n, severity)
    lo = segment_index * quota
    nodes = pool[lo:lo + quota] if lo + quota <= len(pool) else []

    def take(k):
        out, nodes[:] = nodes[:k], nodes[k:]
        return out

    def link_pair():
        s = int(rng.integers(0, n))
        d = int(rng.integers(0, n - 1))
        return s, d if d < s else d + 1

    ops, kinds = [], []

    def add(kind, op):
        kinds.append(kind)
        ops.append(op)

    # --- Draw 1: the boundary straddler (always first; the trailing-
    # draw contract anchors every later rung after it).  Each variant
    # is mid-fault at round ``end`` — suspicion in flight, a link
    # mid-outage, or a loss window across the edge.
    def edge_crash():
        at = end - int(rng.integers(4, 17))
        add("edge_crash", cs.Crash(take(1)[0], at_round=at,
                                   until_round=end
                                   + int(rng.integers(8, 33))))

    def edge_flap():
        s, d = link_pair()
        add("edge_flap", cs.FlappingLink(
            s, d, from_round=end - 12, n_cycles=2,
            down_rounds=6, up_rounds=6))

    def edge_loss():
        s, d = link_pair()
        add("edge_loss", cs.LinkLoss(
            s, d, loss=float(rng.choice([0.4, 0.6])),
            from_round=end - int(rng.integers(8, 17)),
            until_round=end + int(rng.integers(8, 17))))

    edges = [edge_flap, edge_loss] + ([edge_crash] if nodes else [])
    edges[int(rng.integers(0, len(edges)))]()

    # --- Severity-tier interior draws (the generate_scenario menus,
    # revive-only: the stream must return to full strength so it can
    # run forever).
    def op_crash_revive():
        at = start + int(rng.integers(8, segment_rounds // 2))
        add("crash_revive", cs.Crash(take(1)[0], at_round=at,
                                     until_round=at + revive_down))

    def op_flap():
        s, d = link_pair()
        add("flap", cs.FlappingLink(
            s, d,
            from_round=start + int(rng.integers(0, segment_rounds - 64)),
            n_cycles=3, down_rounds=4, up_rounds=6))

    def op_brownout():
        half = n // 2
        add("brownout", cs.Brownout(
            src=(0, half), dst=(half, n),
            peak_loss=float(rng.choice([0.3, 0.5])),
            from_round=start + int(rng.integers(0, segment_rounds - 64)),
            ramp_rounds=12, hold_rounds=10))

    def op_loss_window():
        s, d = link_pair()
        at = start + int(rng.integers(0, segment_rounds - 72))
        add("loss_window", cs.LinkLoss(
            s, d, loss=float(rng.choice([0.3, 0.5])),
            from_round=at, until_round=at + int(rng.integers(24, 65))))

    def op_burst():
        sz = int(rng.integers(2, 4))
        at = start + int(rng.integers(8, segment_rounds // 2))
        picked = take(sz)
        if len(picked) < 2:       # quota exhausted mid-draw: degrade
            nodes[:0] = picked    # (put back; link weather instead)
            return op_loss_window()
        add("burst", cs.CrashBurst(tuple(picked), at_round=at,
                                   until_round=at + revive_down))

    def op_churn():
        picked = take(4)
        if len(picked) < 4:
            nodes[:0] = picked
            return op_loss_window()
        add("churn", cs.ChurnStorm(
            tuple(picked), wave_size=2,
            start_round=start + int(rng.integers(2, 17)),
            wave_every=int(rng.integers(6, 13)),
            down_rounds=revive_down))

    if severity == "mild":
        menu = [op_crash_revive if nodes else op_loss_window,
                op_flap, op_loss_window]
        menu[int(rng.integers(0, len(menu)))]()
    elif severity == "moderate":
        menu = [op_crash_revive if nodes else op_loss_window,
                op_flap, op_brownout, op_burst, op_loss_window]
        for f in rng.choice(len(menu), size=2, replace=False):
            menu[int(f)]()
    else:                                           # severe
        menu = [op_churn, op_brownout, op_flap, op_burst,
                op_crash_revive if nodes else op_loss_window]
        for f in rng.choice(len(menu), size=3, replace=False):
            menu[int(f)]()

    # --- Trailing open-world rung: a NET-ZERO join storm for half the
    # moderate/severe segments with node quota left — permanent
    # crashes re-admitted as fresh identities, slot occupancy restored
    # (pending joins straddle the boundary when the lag carries them
    # past ``end``).  TRAILS every tier draw, the growth contract.
    if (severity != "mild" and len(nodes) >= 4
            and rng.integers(0, 2)):
        lag = int(params.suspicion_rounds) + int(rng.integers(4, 13))
        add("join_storm", cs.ChurnStorm(
            tuple(take(4)), wave_size=2,
            start_round=start + int(rng.integers(8,
                                                 segment_rounds - 63)),
            wave_every=lag + int(rng.integers(2, 7)),
            join_wave_size=2, join_lag=lag, arrivals=()))

    # --- Trailing config rung (the metadata KV plane): half the
    # segments push a fresh value for key 0 from a ROLLING quorum-
    # reserve owner — the config plane soaks under the same weather
    # the failure detector does.  Owners rotate through the reserve
    # ring (disjoint from the fault pool, so a pusher is never
    # node-down at push time); the draw TRAILS every earlier rung so
    # historical streams replay bit-identically.
    if rng.integers(0, 2):
        from scalecube_cluster_tpu.models import metadata

        ring = _config_owner_ring(seed, n, severity)
        add("config_push", cs.ConfigPush(
            node=ring[segment_index % len(ring)], key=0,
            value=int(rng.integers(1, metadata.MD_VALUE_MAX + 1)),
            at_round=start + int(rng.integers(8, segment_rounds - 31))))

    return SoakSegment(
        index=segment_index, round_start=start, round_end=end,
        kinds=tuple(kinds), ops=tuple(ops),
        spans_boundary=_spans(ops[0], end),
    )


def _spans(op, edge: int) -> bool:
    """Does ``op``'s fault window contain ``edge``?  (The boundary
    straddler's defining property; computed from the op itself so the
    pin test asserts it rather than trusting the draw.)"""
    if isinstance(op, cs.Crash):
        return op.at_round < edge < op.until_round
    if isinstance(op, cs.FlappingLink):
        span = op.n_cycles * (op.down_rounds + op.up_rounds)
        return op.from_round < edge < op.from_round + span
    if isinstance(op, cs.LinkLoss):
        return op.from_round < edge < op.until_round
    return False


def soak_schedule(seed: int, n_segments: int, n: int = 32,
                  severity: str = "moderate",
                  segment_rounds: int = DEFAULT_SEGMENT_ROUNDS,
                  params=None) -> "cs.Scenario":
    """Materialize segments ``[0, n_segments)`` into ONE scenario:
    ``horizon = n_segments * segment_rounds``, ops concatenated in
    stream order (each already carrying global rounds), background
    loss fixed per severity.  The last segment's straddler spills past
    the horizon — scheduled rounds beyond it simply never execute, the
    open-horizon property."""
    if n_segments < 1:
        raise ValueError(f"n_segments must be >= 1, got {n_segments}")
    segments = [
        soak_segment(seed, i, n=n, severity=severity,
                     segment_rounds=segment_rounds, params=params)
        for i in range(n_segments)
    ]
    ops = tuple(op for seg in segments for op in seg.ops)
    return cs.Scenario(
        name=f"soak-{severity}-{seed}-x{n_segments}",
        n_members=n, horizon=n_segments * segment_rounds, ops=ops,
        loss_probability=_STREAM_LOSS[severity], seed=seed,
        severity=severity,
    )
