"""Drift probes + the per-segment invariant verdict for the soak.

Host-side only, never journaled: RSS is nondeterministic and the
compile-cache size is process-local, so these samples live in the
soak artifact (bench.py --soak), keeping the journal byte-reproducible
across kill/relaunch — the property the kill drill asserts.

Kept OUT of soak/driver.py on purpose: the swimlint supervised-entry
rule (analysis/rules.py SUPERVISED_ENTRY_POINTS) forbids the soak
driver any direct reach into models/compose.py — the cache-size probe
reads ``run_composed``'s jit cache ATTRIBUTE (introspection, not scan
access), which the call-graph rule can't tell apart from a call, so
the probe lives outside the driver's frontier.
"""

from __future__ import annotations

import os
from typing import List, Optional


def cache_size_probe() -> int:
    """Compile count of the composed program so far in this process
    (-1 when the jit cache API is absent).  Module-level so the
    drift-trip test can monkeypatch a deliberately-growing probe."""
    from scalecube_cluster_tpu.models import compose

    fn = compose.run_composed
    if hasattr(fn, "_cache_size"):
        return int(fn._cache_size())
    return -1  # pragma: no cover — current JAX exposes it


def rss_kb() -> int:
    """Current resident set size in KiB (/proc/self/statm; 0 where
    unavailable — the bound check then degrades to vacuous truth
    rather than a crash on exotic hosts)."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE") // 1024
    except (OSError, ValueError, IndexError):  # pragma: no cover
        return 0


def drift_verdict(samples: List[dict], rss_limit_mb: float,
                  monitor: Optional[dict]) -> dict:
    """Fold per-segment drift samples into the invariant verdict.

    ``compile_flat``: the compose program's cache size is identical
    across every sample AFTER the first executed segment of this
    process (the first pays the one legitimate compile; any later
    growth is recompile drift).  ``rss_bounded``: RSS growth from the
    first sample stays under ``rss_limit_mb``.  ``violations``: the
    monitor's exact total (0 required)."""
    sizes = [s["cache_size"] for s in samples]
    rss = [s["rss_kb"] for s in samples]
    compile_flat = (len(sizes) > 0
                    and all(s == sizes[0] for s in sizes)
                    and sizes[0] >= 0)
    rss_growth_mb = ((max(rss) - rss[0]) / 1024.0) if rss else 0.0
    violations = int((monitor or {}).get("total_violations", -1))
    return {
        "segments_sampled": len(samples),
        "cache_sizes": sizes,
        "compile_flat": bool(compile_flat),
        "rss_first_kb": rss[0] if rss else 0,
        "rss_peak_kb": max(rss) if rss else 0,
        "rss_growth_mb": round(rss_growth_mb, 3),
        "rss_bounded": bool(rss_growth_mb <= rss_limit_mb),
        "violations": violations,
        "monitor_green": bool((monitor or {}).get("green", False)),
        "ok": bool(compile_flat and rss_growth_mb <= rss_limit_mb
                   and violations == 0),
    }
