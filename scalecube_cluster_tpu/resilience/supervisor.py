"""The resilient run supervisor: every run shape, in checkpointed
segments, with retry, rotation and gap-free resumable telemetry.

One driver for the repo's four run shapes —

  - ``plain``     ``models/swim.run``
  - ``traced``    ``models/swim.run_traced`` (membership event trace)
  - ``monitored`` ``chaos/monitor.run_monitored`` (invariant monitor)
  - ``composed``  ``models/compose.run_composed`` (the FULL stack:
    trace ⊕ monitor ⊕ metrics in one program — the soak shape; each
    segment additionally journals a windowed ``metrics_window`` row)

— each executed as a sequence of ``segment_rounds``-round segments.
After every segment, in this order (the trace-first/checkpoint-second
ordering ``utils/checkpoint.run_checkpointed`` established):

  1. the segment's telemetry (digested counters + decoded trace events
     + monitor verdict progress) is APPENDED to a JSONL journal
     (telemetry/sink.TelemetrySink in path/append mode, flushed per
     record);
  2. the carry (SwimState + per-shape aux arrays) is checkpointed into
     the generation-rotated, checksummed store (resilience/store.py).

A preemption between the two re-runs the segment on resume and the
journal's round cursor (``sink.covered_upto``) dedups the re-written
record, so the merged journal of ANY kill/relaunch sequence holds every
round exactly once — no holes, no duplicates.  Runs are bit-reproducible
(every draw is a pure function of (key, round) — ops/prng.py), so the
resumed final state is bit-identical to an uninterrupted run; the
kill-injection harness (resilience/harness.py) asserts exactly that
with real SIGKILLs.

Segment execution is wrapped in bounded exponential-backoff retry with
jitter (:class:`RetryPolicy`): transient device/host errors (a
flaky backend init, an OOM-killed compile server, an I/O hiccup) are
retried from the segment's host-side carry copy — every attempt
re-transfers from host numpy, so donated device buffers from a failed
attempt are never reused.  Deterministic failures (shape/meta
mismatch: ``ValueError``/``TypeError``/``KeyError``/``AssertionError``)
raise immediately — retrying a wrong-config resume can only burn the
preemption budget.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import random
import signal
import time
from typing import Callable, Optional

import numpy as np

RUN_SHAPES = ("plain", "traced", "monitored", "composed")

# Env var the kill harness uses to arm a kill inside a child process:
# "<round>:<stage>" (see KillPlan.from_env).
KILL_ENV = "SCALECUBE_RESILIENCE_KILL"

KILL_STAGES = ("pre_journal", "mid_journal", "post_journal",
               "post_checkpoint")


# --------------------------------------------------------------------------
# Retry policy + classification
# --------------------------------------------------------------------------


#: Deterministic-failure types: retrying cannot change the outcome, so
#: they raise immediately (meta/shape mismatch, bad arguments).
NON_RETRYABLE = (ValueError, TypeError, KeyError, AssertionError)


def is_retryable(exc: BaseException) -> bool:
    """Transient (True) vs deterministic (False) — module docstring.
    Anything not in :data:`NON_RETRYABLE` is presumed transient:
    RuntimeError covers jaxlib's XlaRuntimeError family, OSError the
    host I/O family."""
    return isinstance(exc, Exception) and not isinstance(exc, NON_RETRYABLE)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff with jitter around one segment.

    Delay before retry k (0-based) is
    ``min(base_delay_s * 2**k, max_delay_s) * (1 + jitter * u)`` with
    ``u ~ U[0, 1)`` drawn from a generator seeded by (seed, label) —
    deterministic per call site, decorrelated across segments (the
    thundering-herd argument for jitter, scaled down to one host).
    """

    max_attempts: int = 4
    base_delay_s: float = 0.25
    max_delay_s: float = 8.0
    jitter: float = 0.5
    seed: int = 0


def with_retry(fn: Callable, policy: RetryPolicy, label: str = "",
               log=None, sleep: Callable[[float], None] = time.sleep):
    """Run ``fn()`` under ``policy``; non-retryable errors propagate
    immediately, the last transient error propagates after the attempt
    budget is spent."""
    rng = random.Random(f"{policy.seed}:{label}")
    for attempt in range(policy.max_attempts):
        try:
            return fn()
        except BaseException as e:  # noqa: BLE001 — classified below
            if not is_retryable(e) or attempt == policy.max_attempts - 1:
                raise
            delay = min(policy.base_delay_s * (2 ** attempt),
                        policy.max_delay_s)
            delay *= 1.0 + policy.jitter * rng.random()
            if log is not None:
                log.warning(
                    "%s: attempt %d/%d failed (%s: %s); retrying in "
                    "%.2fs", label or "segment", attempt + 1,
                    policy.max_attempts, type(e).__name__, e, delay,
                )
            sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover


# --------------------------------------------------------------------------
# Kill injection (the harness's fault lever)
# --------------------------------------------------------------------------


class SimulatedPreemption(BaseException):
    """In-process stand-in for SIGKILL (KillPlan mode="raise") — a
    BaseException so neither retry nor the supervisor absorbs it, like
    the real signal absorbs nothing."""


@dataclasses.dataclass(frozen=True)
class KillPlan:
    """Kill the process at the first segment boundary whose
    ``round_end`` >= ``round``, at write-stage ``stage``:

      pre_journal      before the segment record is written (journal
                       AND checkpoint behind — the whole segment
                       re-runs on resume);
      mid_journal      after HALF the record's bytes are written and
                       flushed — a torn trailing line the readers must
                       skip (telemetry/sink.read_records);
      post_journal     record durable, checkpoint behind — the re-run
                       segment's record is DEDUPED on resume;
      post_checkpoint  both durable — resume continues with the next
                       segment.

    ``mode="sigkill"`` delivers a real ``SIGKILL`` to this process (no
    cleanup, no atexit — the preemption shape); ``mode="raise"`` throws
    :class:`SimulatedPreemption` for in-process tests.
    """

    round: int
    stage: str = "post_journal"
    mode: str = "sigkill"

    def __post_init__(self):
        if self.stage not in KILL_STAGES:
            raise ValueError(f"stage {self.stage!r} not in {KILL_STAGES}")
        if self.mode not in ("sigkill", "raise"):
            raise ValueError(f"mode {self.mode!r}")

    def fire(self):
        if self.mode == "raise":
            raise SimulatedPreemption(
                f"simulated preemption at round {self.round} "
                f"({self.stage})"
            )
        os.kill(os.getpid(), signal.SIGKILL)  # pragma: no cover

    @staticmethod
    def from_env(env: Optional[str] = None) -> Optional["KillPlan"]:
        """Parse the harness's ``<round>:<stage>`` env encoding."""
        raw = os.environ.get(KILL_ENV) if env is None else env
        if not raw:
            return None
        round_s, _, stage = raw.partition(":")
        return KillPlan(round=int(round_s),
                        stage=stage or "post_journal")

    def encode(self) -> str:
        return f"{self.round}:{self.stage}"


# --------------------------------------------------------------------------
# Shape drivers: pack/unpack + segment runners
# --------------------------------------------------------------------------


class RunShape:
    """Names for the four run shapes (plain str values so they embed
    directly in meta/journal JSON)."""

    PLAIN = "plain"
    TRACED = "traced"
    MONITORED = "monitored"
    COMPOSED = "composed"


def _default_trace_capacity(params) -> int:
    # Per-segment trace capacity policy shared with bench.py: the scan
    # functionally updates the whole lane buffer on event rounds, so an
    # oversized buffer IS overhead at small N.
    from scalecube_cluster_tpu.telemetry import trace as ttrace

    return min(ttrace.DEFAULT_CAPACITY, max(4 * params.n_members, 4096))


def _initial_carry(shape: str, params, world, opts: dict) -> dict:
    """Fresh host-side carry arrays for ``shape`` (flat dict — the
    checkpoint payload; resilience/store.py module docstring)."""
    from scalecube_cluster_tpu.models import swim
    from scalecube_cluster_tpu.utils import checkpoint as ckpt

    arrays = ckpt.state_to_arrays(swim.initial_state(params, world))
    if shape == RunShape.TRACED:
        full = np.full((params.n_members, params.n_subjects),
                       np.iinfo(np.int32).max, dtype=np.int32)
        arrays["telemetry/first_suspect"] = full
        arrays["telemetry/first_removed"] = full.copy()
    elif shape == RunShape.MONITORED:
        from scalecube_cluster_tpu.chaos import monitor as cmon

        arrays.update(
            cmon.MonitorState.init(opts["monitor_capacity"]).to_arrays()
        )
    elif shape == RunShape.COMPOSED:
        from scalecube_cluster_tpu.chaos import monitor as cmon
        from scalecube_cluster_tpu.telemetry import metrics as tmetrics

        full = np.full((params.n_members, params.n_subjects),
                       np.iinfo(np.int32).max, dtype=np.int32)
        arrays["telemetry/first_suspect"] = full
        arrays["telemetry/first_removed"] = full.copy()
        arrays.update(
            cmon.MonitorState.init(opts["monitor_capacity"]).to_arrays()
        )
        ms = tmetrics.MetricsState.init(opts["metrics_spec"])
        arrays.update(_metrics_to_arrays(ms))
    return arrays


def _metrics_to_arrays(ms) -> dict:
    """MetricsState -> flat checkpoint-payload keys (the ``metrics/``
    namespace of the composed shape's carry)."""
    out = {"metrics/counters": np.asarray(ms.counters),
           "metrics/gauges": np.asarray(ms.gauges)}
    for name, v in ms.hists.items():
        out[f"metrics/hist/{name}"] = np.asarray(v)
    return out


def _metrics_from_arrays(carry: dict, spec):
    """The inverse of :func:`_metrics_to_arrays` (hist order from the
    spec — the carry dict is flat and unordered)."""
    from scalecube_cluster_tpu.telemetry import metrics as tmetrics

    return tmetrics.MetricsState(
        counters=carry["metrics/counters"],
        gauges=carry["metrics/gauges"],
        hists={name: carry[f"metrics/hist/{name}"]
               for name, _ in spec.histograms},
    )


def _run_segment(shape: str, key, params, world, start: int, end: int,
                 carry: dict, opts: dict):
    """One segment from host-side ``carry`` arrays; returns
    ``(new_carry_arrays, journal_record_payload)`` — everything host-
    side numpy, so a retry can simply call again (donated device
    buffers are re-created from the host copy per attempt)."""
    import jax

    from scalecube_cluster_tpu.models import swim
    from scalecube_cluster_tpu.telemetry import sink as tsink
    from scalecube_cluster_tpu.utils import checkpoint as ckpt

    state = ckpt.state_from_arrays(
        {k[len("state/"):]: v for k, v in carry.items()
         if k.startswith("state/")},
        params=params,
    )
    step = end - start
    common = dict(state=state, start_round=start, knobs=opts.get("knobs"),
                  shift_key=opts.get("shift_key"))
    record = {"shape": shape, "round_start": start, "round_end": end}

    if shape == RunShape.PLAIN:
        new_state, metrics = swim.run(key, params, world, step, **common)
        aux_out, extras = {}, {}
    elif shape == RunShape.TRACED:
        from scalecube_cluster_tpu.telemetry import trace as ttrace

        cap = opts["trace_capacity"]
        tel_in = ttrace.TelemetryState.resume(
            carry["telemetry/first_suspect"],
            carry["telemetry/first_removed"], capacity=cap,
        )
        new_state, tel_out, metrics = swim.run_traced(
            key, params, world, step, trace_capacity=cap,
            telemetry=tel_in, **common,
        )
        (lanes, count, dropped), fs, fr = jax.device_get((
            (tel_out.trace.lanes, tel_out.trace.count,
             tel_out.trace.dropped),
            tel_out.first_suspect, tel_out.first_removed,
        ))
        events = ttrace.decode_events(ttrace.EventTrace(
            lanes=lanes, count=count, dropped=dropped,
        ))
        aux_out = {"telemetry/first_suspect": np.asarray(fs),
                   "telemetry/first_removed": np.asarray(fr)}
        extras = {
            "events": [e.to_json() for e in events],
            "events_recorded": int(count),
            "events_dropped": int(dropped),
        }
    elif shape == RunShape.MONITORED:
        from scalecube_cluster_tpu.chaos import monitor as cmon

        mon_in = cmon.MonitorState.from_arrays(carry)
        new_state, mon_out, metrics = cmon.run_monitored(
            key, params, world, opts["spec"], step,
            capacity=opts["monitor_capacity"], monitor=mon_in, **common,
        )
        mon_host = jax.device_get(mon_out)
        aux_out = mon_host.to_arrays()
        extras = {"monitor": cmon.verdict(mon_host, max_evidence=8)}
    elif shape == RunShape.COMPOSED:
        from scalecube_cluster_tpu.chaos import monitor as cmon
        from scalecube_cluster_tpu.models import compose
        from scalecube_cluster_tpu.telemetry import metrics as tmetrics
        from scalecube_cluster_tpu.telemetry import trace as ttrace

        cap = opts["trace_capacity"]
        mspec = opts["metrics_spec"]
        tel_in = ttrace.TelemetryState.resume(
            carry["telemetry/first_suspect"],
            carry["telemetry/first_removed"], capacity=cap,
        )
        new_state, results, metrics = compose.run_composed(
            key, params, world, step,
            monitor_spec=opts["spec"], trace_capacity=cap,
            metrics_spec=mspec,
            monitor_capacity=opts["monitor_capacity"],
            telemetry=tel_in,
            monitor=cmon.MonitorState.from_arrays(carry),
            metrics_state=_metrics_from_arrays(carry, mspec),
            **common,
        )
        tel_out = results["trace"]
        (lanes, count, dropped), fs, fr = jax.device_get((
            (tel_out.trace.lanes, tel_out.trace.count,
             tel_out.trace.dropped),
            tel_out.first_suspect, tel_out.first_removed,
        ))
        events = ttrace.decode_events(ttrace.EventTrace(
            lanes=lanes, count=count, dropped=dropped,
        ))
        mon_host = jax.device_get(results["monitor"])
        ms_host = jax.device_get(results["metrics"])
        aux_out = {"telemetry/first_suspect": np.asarray(fs),
                   "telemetry/first_removed": np.asarray(fr)}
        aux_out.update(mon_host.to_arrays())
        # The metrics registry is WINDOWED per segment: this segment's
        # values journal as their own metrics_window row (the
        # stream_metered_run row shape) and the carry resumes from the
        # reset — gauges sample through, counters/hists restart.
        aux_out.update(_metrics_to_arrays(tmetrics.reset_window(ms_host)))
        extras = {
            "events": [e.to_json() for e in events],
            "events_recorded": int(count),
            "events_dropped": int(dropped),
            "monitor": cmon.verdict(mon_host, max_evidence=8),
            # Popped (never journaled inside the segment record) by
            # run_resilient and written as a metrics_window row with
            # its own dedup cursor.
            "_metrics_window": {
                "round_start": start, "round_end": end,
                **tmetrics.to_json(ms_host, mspec),
            },
        }
    else:
        raise ValueError(f"unknown run shape {shape!r}; "
                         f"expected one of {RUN_SHAPES}")

    jax.block_until_ready(new_state.status)
    new_carry = ckpt.state_to_arrays(new_state)
    new_carry.update(aux_out)
    record["counters"] = tsink.counters_row(
        jax.device_get(metrics), round_offset=start
    )
    record.update(extras)
    return new_carry, record


# --------------------------------------------------------------------------
# The supervisor
# --------------------------------------------------------------------------


@dataclasses.dataclass
class ResilientRunResult:
    """What :func:`run_resilient` hands back (host side)."""

    state: object                 # final SwimState (rebuilt from the
                                  # host-side checkpoint payload)
    carry_arrays: dict            # full final checkpoint payload
    next_round: int
    journal_path: str
    segments_run: int             # segments executed by THIS process
    segments_deduped: int         # re-runs whose records were deduped
    resumed_from: Optional[dict]  # store.load_latest info, or None
    retries: int                  # transient-failure retries consumed
    events_recorded: int = 0      # traced: this process's total
    events_dropped: int = 0
    monitor_verdict: Optional[dict] = None   # monitored: final verdict
    alarm_transitions: int = 0    # alarm_transition rows THIS process wrote


def _spec_digest(spec) -> str:
    """Stable digest of a MonitorSpec (complete_by array + flag) for
    the meta-mismatch check."""
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(np.asarray(spec.complete_by)).tobytes())
    h.update(b"1" if spec.check_false_suspicion else b"0")
    return h.hexdigest()[:12]


def _world_digest(world) -> str:
    """Stable digest of the FULL fault schedule (every SwimWorld leaf:
    crash/leave/revive rounds, link-fault rules, partition phases,
    seeds).  config_digest covers SwimParams only — without this a
    relaunch against a different scenario would be silently adopted as
    the same run and produce a state matching neither."""
    import jax

    h = hashlib.sha256()
    leaves, treedef = jax.tree_util.tree_flatten(world)
    h.update(repr(treedef).encode())
    for leaf in leaves:
        a = np.ascontiguousarray(np.asarray(leaf))
        h.update(str(a.dtype).encode())
        h.update(repr(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:12]


def run_resilient(shape: str, key, params, world, n_rounds: int, *,
                  store, segment_rounds: int = 256,
                  journal_path: Optional[str] = None,
                  meta: Optional[dict] = None,
                  knobs=None, shift_key=None, spec=None,
                  trace_capacity: Optional[int] = None,
                  monitor_capacity: int = 1 << 12,
                  metrics_spec=None,
                  retry: Optional[RetryPolicy] = None,
                  kill_plan: Optional[KillPlan] = None,
                  alarm_specs=None,
                  on_segment: Optional[Callable[[dict], None]] = None,
                  log=None, sleep=time.sleep) -> ResilientRunResult:
    """Drive ``shape`` over ``n_rounds`` rounds with checkpointed
    segments, retry, and a resumable journal (module docstring).

    ``store`` is a :class:`resilience.store.CheckpointStore`; the
    journal defaults to ``<store.base_path>.journal.jsonl``.  On resume
    the stored meta must equal this call's (shape, config digest,
    n_rounds, segment grid, user ``meta``) — a mismatch raises
    ``ValueError`` immediately (non-retryable by definition: it means
    the caller is trying to continue a DIFFERENT run).  ``spec`` is
    required for the monitored shape (chaos/monitor.MonitorSpec).

    ``alarm_specs`` (``telemetry.alarms.AlarmSpec`` sequence) evaluates
    every segment's counter row through a live alarm engine at the
    segment boundary and journals each state change as an
    ``alarm_transition`` record — AFTER the segment record and before
    the checkpoint, so a preemption can strand a durable segment with
    its transitions missing.  The resume scan replays the journal
    through a fresh engine and writes exactly the missing tail
    (telemetry/alarms.py replay/dedup), so alarm rows keep the
    journal's exactly-once guarantee across any kill/relaunch sequence.

    The ``composed`` shape (the soak harness's) runs the FULL
    instrumented stack through ``models/compose.run_composed`` and
    journals each segment's windowed metrics registry as a
    ``metrics_window`` row right after the segment record, deduped on
    its OWN journal cursor — a kill between the two writes duplicates
    neither on resume.  ``on_segment(record)`` (host callback, never
    journaled — keep it deterministic-output-free) fires once per
    segment EXECUTED by this process, after its checkpoint: the soak
    driver's drift-invariant sampling point.

    ``kill_plan`` is the harness's fault lever — None in production.
    """
    from scalecube_cluster_tpu.telemetry import sink as tsink
    from scalecube_cluster_tpu.utils import checkpoint as ckpt

    if shape not in RUN_SHAPES:
        raise ValueError(f"unknown run shape {shape!r}; "
                         f"expected one of {RUN_SHAPES}")
    if shape in (RunShape.MONITORED, RunShape.COMPOSED) and spec is None:
        raise ValueError(f"{shape} shape needs a MonitorSpec (spec=)")
    if shape == RunShape.COMPOSED and metrics_spec is None:
        from scalecube_cluster_tpu.telemetry import metrics as tmetrics

        metrics_spec = tmetrics.MetricsSpec()
    if segment_rounds < 1:
        raise ValueError(f"segment_rounds must be >= 1, "
                         f"got {segment_rounds}")
    retry = retry or RetryPolicy()
    journal_path = journal_path or f"{store.base_path}.journal.jsonl"

    opts = {
        "knobs": knobs, "shift_key": shift_key, "spec": spec,
        "monitor_capacity": monitor_capacity,
        "trace_capacity": trace_capacity or _default_trace_capacity(params),
        "metrics_spec": metrics_spec,
    }
    # The resume-identity pin: everything that must not change under a
    # relaunch.  segment_rounds is included because the journal's dedup
    # cursor only composes with a stable segment grid — resuming with a
    # different grid would write records overlapping already-journaled
    # rounds.
    full_meta = json.loads(json.dumps({
        "shape": shape,
        "config_digest": tsink.config_digest(params),
        "world_digest": _world_digest(world),
        "n_rounds": n_rounds,
        "segment_rounds": segment_rounds,
        "spec_digest": _spec_digest(spec) if spec is not None else None,
        # Capacities change observable behavior for their shape (per-
        # segment drop points; the monitor buffer's lane shape), so
        # they join the pin where they matter and stay None elsewhere.
        "trace_capacity": (opts["trace_capacity"]
                           if shape in (RunShape.TRACED,
                                        RunShape.COMPOSED) else None),
        "monitor_capacity": (monitor_capacity
                             if shape in (RunShape.MONITORED,
                                          RunShape.COMPOSED) else None),
        "user": meta or {},
    }))

    loaded = store.load_latest(log=log)
    legacy = False
    if loaded is not None:
        carry, cursor, saved_key, saved_meta, info = loaded
        legacy = bool(info.get("legacy"))
        if saved_key is not None:
            key = saved_key
        # A legacy single-file checkpoint (utils/checkpoint.save, pre-
        # rotation — MIGRATING.md) stored only the CALLER's meta dict,
        # so the adoption check compares against the user part; rotated
        # generations carry the full resume-identity pin.
        expected = full_meta["user"] if legacy else full_meta
        if saved_meta != expected:
            raise ValueError(
                f"checkpoint meta mismatch: saved {saved_meta!r} != "
                f"current {expected!r} — refusing to resume a "
                f"different run"
            )
        if legacy and shape != RunShape.PLAIN:
            raise ValueError(
                f"legacy single-file checkpoint {info['path']!r} holds "
                f"only the plain-run carry; cannot adopt it into a "
                f"{shape!r} run (its aux arrays never existed)"
            )
        if log is not None:
            log.info("resumed %s from %s at round %d (%d corrupt "
                     "generation(s) skipped)", shape, info["path"],
                     cursor, len(info["fallbacks"]))
    else:
        carry, cursor, info = _initial_carry(shape, params, world,
                                             opts), 0, None

    # The sink heals a torn trailing line at reopen (append=True)
    # BEFORE the journal is classified below, so the freshness check
    # sees the durable byte count: a journal whose only content is a
    # torn first line (writer killed mid-manifest-write) heals to
    # empty and is still FRESH — its manifest gets written.
    sink = tsink.TelemetrySink(path=journal_path, append=True)
    killed_stage_armed = kill_plan is not None
    retries = 0

    def attempt_counter(fn, label):
        nonlocal retries

        def counted():
            nonlocal retries
            try:
                return fn()
            except BaseException as e:  # noqa: BLE001 — re-raised
                if is_retryable(e):
                    retries += 1
                raise

        return with_retry(counted, retry, label=label, log=log,
                          sleep=sleep)

    engine = existing = None
    replayed_transitions: list = []
    if alarm_specs:
        from scalecube_cluster_tpu.telemetry import alarms as talarms

        engine = talarms.AlarmEngine(alarm_specs, kinds=("segment",))

    try:
        fresh_journal = os.path.getsize(journal_path) == 0
        # ONE scan of the durable journal serves every resume consumer:
        # the segment dedup cursor AND the alarm-engine replay — a long
        # journal is parsed once, not once per reader (the
        # JournalFollower cursor; its covered_upto is the rebased
        # tsink.covered_upto).
        covered = covered_win = 0
        if not fresh_journal:
            follower = tsink.follow_records(journal_path)
            records = follower.poll()
            covered = follower.covered_upto(kind="segment")
            covered_win = follower.covered_upto(kind="metrics_window")
            if engine is not None:
                replayed_transitions, existing = talarms.replay_journal(
                    engine, records)
        if legacy and fresh_journal:
            # Adopting a pre-journal lineage: rounds [0, cursor) were
            # run before this journal existed, so its coverage contract
            # starts at the adoption cursor (recorded in the manifest
            # below) — not a hole, a documented origin.
            covered = cursor
        elif covered < cursor:
            # The journal write precedes the checkpoint save, so a kill
            # can only ever leave the journal AHEAD of the cursor —
            # behind it means records were lost out-of-band (deleted/
            # rewritten journal next to surviving checkpoints).
            # Continuing would leave a silent interior hole in the
            # telemetry; same contract as utils/checkpoint
            # .run_checkpointed's missing-trace refusal.
            raise ValueError(
                f"journal {journal_path!r} covers rounds [0, {covered}) "
                f"but the checkpoint cursor is {cursor} — rounds "
                f"[{covered}, {cursor}) were lost out-of-band; restore "
                f"the journal or delete the checkpoint lineage to "
                f"start over"
            )
        if fresh_journal:
            sink.write_manifest(params=params, workload={
                "kind": "resilient_run", "journal_origin": covered,
                "legacy_adoption": legacy, **full_meta,
            })
        elif info is not None or covered:
            sink.write_record("resume", {
                "round_cursor": cursor,
                "journal_covered": covered,
                "checkpoint": None if info is None else {
                    "path": info["path"],
                    "generation": info.get("generation"),
                    "fallbacks": info["fallbacks"],
                },
            })

        segments_run = deduped = 0
        events_recorded = events_dropped = 0
        monitor_verdict = None
        alarm_written = 0
        if engine is not None and replayed_transitions:
            # The dead process may have been killed between a segment
            # record and its alarm transitions (or mid-transition-list):
            # the replay regenerated the full deterministic list, the
            # count dedup writes exactly what is missing.
            alarm_written += len(talarms.write_transitions(
                sink, replayed_transitions, existing))
        r = cursor
        while r < n_rounds:
            end = min(r + segment_rounds, n_rounds)
            new_carry, record = attempt_counter(
                lambda: _run_segment(shape, key, params, world, r, end,
                                     carry, opts),
                label=f"{shape}-segment@{r}",
            )
            window = record.pop("_metrics_window", None)
            record["checkpoint_generation"] = end
            events_recorded += record.get("events_recorded", 0)
            events_dropped += record.get("events_dropped", 0)
            monitor_verdict = record.get("monitor", monitor_verdict)

            due_kill = (killed_stage_armed and end >= kill_plan.round)
            if due_kill and kill_plan.stage == "pre_journal":
                kill_plan.fire()
            if end > covered:
                if due_kill and kill_plan.stage == "mid_journal":
                    # Half a record then death: the torn-trailing-line
                    # case read_records must absorb.  Raw write on the
                    # sink's stream — this IS the fault injection, not
                    # an API anyone else should use.
                    text = json.dumps({"kind": "segment",
                                       "run_id": sink.run_id, **record})
                    sink._f.write(text[:max(1, len(text) // 2)])
                    sink._f.flush()
                    kill_plan.fire()
                sink.write_record("segment", record)
            else:
                deduped += 1
            if window is not None and end > covered_win:
                # The composed shape's windowed registry row, deduped
                # on its OWN cursor: a kill after the segment write but
                # before this one re-runs the segment on resume, dedups
                # the segment record, and writes exactly this row.
                sink.write_metrics_window(window)
            if due_kill and kill_plan.stage == "post_journal":
                kill_plan.fire()
            if engine is not None and end > covered:
                # Segment-boundary alarm evaluation: transitions land
                # after the segment record and after the post_journal
                # kill point — so that kill stage models a preemption
                # landing mid-transition (segment durable, alarms not),
                # the case the resume replay must repair.  Deduped
                # segments were already replayed at startup.
                alarm_written += len(talarms.write_transitions(
                    sink,
                    engine.observe({"kind": "segment", **record}),
                    existing))
            store.save(new_carry, end, key=key, meta=full_meta)
            if due_kill and kill_plan.stage == "post_checkpoint":
                kill_plan.fire()
            carry = new_carry
            r = end
            segments_run += 1
            if on_segment is not None:
                on_segment(record)
            if log is not None:
                log.info("%s: segment [%d, %d) journaled + "
                         "checkpointed (gen %d)", shape, record
                         ["round_start"], end, end)

        sink.write_summary(
            shape=shape, rounds=n_rounds,
            segments_run=segments_run, retries=retries,
        )
    finally:
        sink.close()

    state = ckpt.state_from_arrays(
        {k[len("state/"):]: v for k, v in carry.items()
         if k.startswith("state/")},
        params=params,
    )
    return ResilientRunResult(
        state=state, carry_arrays=carry, next_round=n_rounds,
        journal_path=journal_path, segments_run=segments_run,
        segments_deduped=deduped, resumed_from=info, retries=retries,
        events_recorded=events_recorded, events_dropped=events_dropped,
        monitor_verdict=monitor_verdict, alarm_transitions=alarm_written,
    )
