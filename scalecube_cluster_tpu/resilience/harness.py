"""Kill-injection harness: real SIGKILLs against the resilient runner.

``utils/checkpoint``'s "kill" test was an in-process simulation — stop
calling the driver, call it again.  A real preemption is harsher: no
Python finally-blocks, no atexit, buffers torn mid-byte.  This harness
runs the supervisor in a SUBPROCESS, SIGKILLs it at a seeded random
round and write-stage (supervisor.KillPlan — before/inside/after the
journal write, after the checkpoint), relaunches until completion, and
then asserts the two headline guarantees:

  - the resumed final state (full checkpoint payload: SwimState + the
    per-shape aux arrays) is BIT-IDENTICAL to an uninterrupted run —
    compared by content digest (resilience/store.payload_checksum);
  - the merged journal is COMPLETE: segment records tile
    ``[0, n_rounds)`` exactly once (no holes, no duplicate rounds), and
    for the traced shape the merged event stream equals the
    uninterrupted run's event for event.

Entry points: :func:`run_drill` (the matrix bench.py --resilience and
experiments/resilience_drill.py drive) and the module's ``__main__``
child mode (``python -m scalecube_cluster_tpu.resilience.harness
--config cfg.json``), which runs one resilient run to completion and
prints a one-line JSON summary.  The kill is armed through the
``SCALECUBE_RESILIENCE_KILL`` env var so the child process needs no
special code path — production and harnessed runs execute the same
supervisor.
"""

from __future__ import annotations

import dataclasses
import json
import os
import pathlib
import random
import signal
import subprocess
import sys
import zlib
from typing import List, Optional

_REPO_ROOT = str(pathlib.Path(__file__).resolve().parents[2])


# --------------------------------------------------------------------------
# Workload config (JSON round-trippable — it rides to the child process)
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DrillConfig:
    """One resilient-run workload, fully determined by its fields (the
    child rebuilds params/world/key from them bit-for-bit)."""

    shape: str
    base_path: str                  # checkpoint-store base (workdir file)
    n_members: int = 24
    n_subjects: int = 16
    n_rounds: int = 48
    segment_rounds: int = 12
    seed: int = 7
    crash_node: int = 3
    crash_round: int = 5
    loss_probability: float = 0.05
    delivery: str = "shift"
    keep_generations: int = 3

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_json(obj: dict) -> "DrillConfig":
        return DrillConfig(**obj)


def build_workload(cfg: DrillConfig):
    """(key, params, world, spec) for one drill config — the sped-up
    protocol preset bench.py's telemetry scenario uses, so suspicion
    resolves inside a short run and the trace/monitor have real events
    to carry across the kill."""
    import jax

    from scalecube_cluster_tpu.chaos import monitor as cmon
    from scalecube_cluster_tpu.config import ClusterConfig
    from scalecube_cluster_tpu.models import swim

    c = ClusterConfig.default().replace(
        gossip_interval=100, ping_interval=200, ping_timeout=100,
        sync_interval=1_000, suspicion_mult=3,
    )
    params = swim.SwimParams.from_config(
        c, n_members=cfg.n_members,
        n_subjects=min(cfg.n_subjects, cfg.n_members),
        loss_probability=cfg.loss_probability, delivery=cfg.delivery,
    )
    world = swim.SwimWorld.healthy(params).with_crash(
        cfg.crash_node, at_round=cfg.crash_round
    )
    spec = (cmon.MonitorSpec.passive(params)
            if cfg.shape == "monitored" else None)
    return jax.random.key(cfg.seed), params, world, spec


def run_config(cfg: DrillConfig, kill_plan=None):
    """One resilient run of ``cfg`` in THIS process (the child body and
    the uninterrupted-reference path)."""
    from scalecube_cluster_tpu.resilience import store as rstore
    from scalecube_cluster_tpu.resilience import supervisor as rsup

    key, params, world, spec = build_workload(cfg)
    store = rstore.CheckpointStore(cfg.base_path,
                                   keep=cfg.keep_generations)
    return rsup.run_resilient(
        cfg.shape, key, params, world, cfg.n_rounds, store=store,
        segment_rounds=cfg.segment_rounds, spec=spec,
        kill_plan=kill_plan,
    )


def result_digest(result) -> str:
    """Content digest of the FULL final carry (SwimState + aux) — the
    bit-identity the harness asserts."""
    from scalecube_cluster_tpu.resilience import store as rstore

    return rstore.payload_checksum(result.carry_arrays)


# --------------------------------------------------------------------------
# Journal verification
# --------------------------------------------------------------------------


def verify_journal(path: str, n_rounds: int) -> dict:
    """No holes, no duplicates: the segment records must tile
    ``[0, n_rounds)`` exactly once, in order."""
    from scalecube_cluster_tpu.telemetry import sink as tsink

    segs = tsink.read_records(path, kind="segment")
    ranges = [(int(r["round_start"]), int(r["round_end"])) for r in segs]
    problems = []
    expected = 0
    for start, end in ranges:
        if start != expected:
            kind = "duplicate rounds" if start < expected else "hole"
            problems.append(
                f"{kind}: segment [{start}, {end}) after coverage "
                f"reached {expected}"
            )
        expected = max(expected, end)
    if expected != n_rounds:
        problems.append(f"coverage ends at {expected}, run had "
                        f"{n_rounds} rounds")
    return {
        "complete": not problems,
        "problems": problems,
        "n_segments": len(ranges),
        "ranges": ranges,
    }


def merged_events(path: str) -> List[dict]:
    """The journal's event stream in round order (traced shape)."""
    from scalecube_cluster_tpu.telemetry import sink as tsink

    out: List[dict] = []
    for rec in tsink.read_records(path, kind="segment"):
        out.extend(rec.get("events", ()))
    return out


# --------------------------------------------------------------------------
# The subprocess driver
# --------------------------------------------------------------------------


def _child_env(extra_env: Optional[dict] = None) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (_REPO_ROOT, env.get("PYTHONPATH")) if p
    )
    env.update(extra_env or {})
    return env


def launch_child(cfg: DrillConfig, cfg_path: str, kill_plan=None,
                 timeout: float = 300.0,
                 extra_env: Optional[dict] = None):
    """One child launch; returns the CompletedProcess.  The kill plan
    rides in SCALECUBE_RESILIENCE_KILL (supervisor.KILL_ENV).

    The child runs with ``cwd=_REPO_ROOT`` (imports must resolve even
    when the driver sits elsewhere), so the config's base path is
    absolutized first — otherwise parent and child would resolve the
    same relative lineage against different directories and the driver
    would verify files the child never wrote."""
    from scalecube_cluster_tpu.resilience import supervisor as rsup

    cfg = dataclasses.replace(
        cfg, base_path=os.path.abspath(cfg.base_path))
    with open(cfg_path, "w") as f:
        json.dump(cfg.to_json(), f)
    env = _child_env(extra_env)
    if kill_plan is not None:
        env[rsup.KILL_ENV] = kill_plan.encode()
    else:
        env.pop(rsup.KILL_ENV, None)
    return subprocess.run(
        [sys.executable, "-m",
         "scalecube_cluster_tpu.resilience.harness", "--config",
         cfg_path],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=_REPO_ROOT,
    )


def run_kill_sequence(cfg: DrillConfig, kill_seed: int, n_kills: int,
                      workdir: str, timeout: float = 300.0,
                      extra_env: Optional[dict] = None) -> dict:
    """SIGKILL the run ``n_kills`` times at seeded random (round, stage)
    points, relaunch to completion, and verify against an uninterrupted
    in-process reference.  Returns the verdict dict for one shape."""
    from scalecube_cluster_tpu.resilience import supervisor as rsup

    os.makedirs(workdir, exist_ok=True)

    # Uninterrupted reference in its own lineage — run as a SUBPROCESS
    # with the same env as the killed children, so the bit-identity
    # comparison never crosses backends (the driver may sit on an
    # accelerator while extra_env pins the children to CPU;
    # float-dependent draws are not guaranteed identical across
    # backends).
    ref_cfg = dataclasses.replace(
        cfg, base_path=os.path.join(workdir, "ref.ckpt"))
    ref_proc = launch_child(
        ref_cfg, os.path.join(workdir, "ref_config.json"),
        kill_plan=None, timeout=timeout, extra_env=extra_env,
    )
    if ref_proc.returncode != 0:
        return {"ok": False, "error": "reference run failed",
                "stderr_tail": ref_proc.stderr[-2000:], "launches": []}
    ref_summary = json.loads(
        [ln for ln in ref_proc.stdout.strip().splitlines() if ln][-1])
    ref_digest = ref_summary["state_digest"]
    ref_events = merged_events(ref_summary["journal"])

    # Seeded kill schedule: strictly increasing rounds so every kill
    # lands in territory the previous relaunch has not yet re-covered,
    # cycling write-stages so each boundary fault class gets exercised.
    rng = random.Random(kill_seed)
    rounds = sorted(rng.sample(range(1, cfg.n_rounds + 1),
                               min(n_kills, cfg.n_rounds)))
    stages = [rsup.KILL_STAGES[rng.randrange(len(rsup.KILL_STAGES))]
              for _ in rounds]
    plans = [rsup.KillPlan(round=r, stage=s)
             for r, s in zip(rounds, stages)]

    cfg_path = os.path.join(workdir, "drill_config.json")
    launches = []
    for plan in plans:
        proc = launch_child(cfg, cfg_path, kill_plan=plan,
                            timeout=timeout, extra_env=extra_env)
        launches.append({
            "kill": plan.encode(), "returncode": proc.returncode,
        })
        if proc.returncode != -signal.SIGKILL:
            # The child survived past its own kill point (e.g. the kill
            # round exceeded the rounds left) — acceptable only if it
            # COMPLETED; anything else is a harness failure.
            if proc.returncode != 0:
                launches[-1]["stderr_tail"] = proc.stderr[-2000:]
                return {"ok": False, "error": "child failed",
                        "launches": launches}
    final = launch_child(cfg, cfg_path, kill_plan=None, timeout=timeout,
                         extra_env=extra_env)
    launches.append({"kill": None, "returncode": final.returncode})
    if final.returncode != 0:
        return {"ok": False, "error": "final relaunch failed",
                "stderr_tail": final.stderr[-2000:],
                "launches": launches}
    summary_lines = [ln for ln in final.stdout.strip().splitlines()
                     if ln]
    summary = json.loads(summary_lines[-1])

    journal = verify_journal(summary["journal"], cfg.n_rounds)
    got_events = merged_events(summary["journal"])
    bit_identical = summary["state_digest"] == ref_digest
    events_match = got_events == ref_events
    return {
        "ok": bool(bit_identical and journal["complete"]
                   and events_match),
        "shape": cfg.shape,
        "bit_identical": bit_identical,
        "state_digest": summary["state_digest"],
        "ref_digest": ref_digest,
        "journal_complete": journal["complete"],
        "journal_problems": journal["problems"],
        "journal_segments": journal["n_segments"],
        "events_match": events_match,
        "events": len(got_events),
        "kills": [p.encode() for p in plans],
        "launches": launches,
        "resumed_segments_final_launch": summary["segments_run"],
    }


def corruption_drill(cfg: DrillConfig, workdir: str) -> dict:
    """The fallback guarantee, demonstrated on a real lineage: complete
    a run, bit-flip the newest generation, and show load_latest recovers
    from the previous intact one (exhaustion of every candidate is
    pinned separately in tests/test_resilience_store.py)."""
    from scalecube_cluster_tpu.resilience import store as rstore

    os.makedirs(workdir, exist_ok=True)
    cfg = dataclasses.replace(
        cfg, base_path=os.path.join(workdir, "corrupt.ckpt"))
    run_config(cfg)
    store = rstore.CheckpointStore(cfg.base_path,
                                   keep=cfg.keep_generations)
    gens = store.generations_on_disk()
    if len(gens) < 2:
        # keep=1, or a run short enough for one segment: there is no
        # previous generation to fall back TO — report red instead of
        # crashing into gens[-2] / exhaustion below.
        return {
            "ok": False,
            "error": f"corruption drill needs >= 2 surviving "
                     f"generations, got {gens}; use keep >= 2 and "
                     f"rounds > segment_rounds",
            "generations": gens,
        }
    latest = store.gen_path(gens[-1])
    # Flip one payload byte mid-file (past the zip local header).
    with open(latest, "rb+") as f:
        f.seek(os.path.getsize(latest) // 2)
        b = f.read(1)
        f.seek(-1, 1)
        f.write(bytes([b[0] ^ 0xFF]))
    _, next_round, _, _, info = store.load_latest()
    fell_back = (info["generation"] == gens[-2]
                 and len(info["fallbacks"]) == 1
                 and next_round == gens[-2])
    return {
        "ok": bool(fell_back),
        "generations": gens,
        "corrupted": latest,
        "loaded_generation": info["generation"],
        "fallbacks": [why for _, why in info["fallbacks"]],
    }


def run_drill(shapes, workdir: str, kill_seed: int = 1234,
              n_kills: int = 1, timeout: float = 300.0,
              extra_env: Optional[dict] = None,
              cfg_overrides: Optional[dict] = None) -> dict:
    """The full matrix: one kill sequence per shape + the corruption
    drill.  Returns the report dict bench.py --resilience prints."""
    report = {"shapes": {}, "kill_seed": kill_seed, "n_kills": n_kills}
    overrides = cfg_overrides or {}
    for shape in shapes:
        shape_dir = os.path.join(workdir, shape)
        cfg = DrillConfig(
            shape=shape,
            base_path=os.path.join(shape_dir, "drill.ckpt"),
            **overrides,
        )
        report["shapes"][shape] = run_kill_sequence(
            cfg, kill_seed=kill_seed + zlib.crc32(shape.encode()) % 1000,
            n_kills=n_kills, workdir=shape_dir, timeout=timeout,
            extra_env=extra_env,
        )
    corrupt_cfg = DrillConfig(
        shape="plain",
        base_path=os.path.join(workdir, "corruption", "drill.ckpt"),
        **overrides,
    )
    report["corruption"] = corruption_drill(
        corrupt_cfg, os.path.join(workdir, "corruption"))
    report["green"] = bool(
        all(v["ok"] for v in report["shapes"].values())
        and report["corruption"]["ok"]
    )
    return report


# --------------------------------------------------------------------------
# Child mode
# --------------------------------------------------------------------------


def child_main(argv=None) -> int:
    """Run one resilient run to completion (the subprocess body).  Arms
    the kill plan from SCALECUBE_RESILIENCE_KILL; on normal completion
    prints one JSON summary line with the state digest + journal path.
    """
    import argparse

    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--config", required=True,
                        help="path to a DrillConfig JSON file")
    args = parser.parse_args(argv)
    with open(args.config) as f:
        cfg = DrillConfig.from_json(json.load(f))

    from scalecube_cluster_tpu.resilience import supervisor as rsup
    from scalecube_cluster_tpu.utils import runlog

    runlog.enable_compilation_cache()
    kill_plan = rsup.KillPlan.from_env()
    result = run_config(cfg, kill_plan=kill_plan)
    print(json.dumps({
        "state_digest": result_digest(result),
        "next_round": result.next_round,
        "segments_run": result.segments_run,
        "segments_deduped": result.segments_deduped,
        "resumed": result.resumed_from is not None,
        "retries": result.retries,
        "journal": result.journal_path,
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(child_main())
