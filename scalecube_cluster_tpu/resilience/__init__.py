"""Preemption-safe resilient running: rotated checksummed checkpoints,
a segment supervisor for every run shape, and a real kill-injection
harness.

The reference cluster keeps no persistent state — a restarted node
rejoins from seeds (SURVEY.md §5.4) — so on this repo's north-star
workloads (1M-member × 10k-round sweeps on preemptible TPUs) the
weakest failure domain is the HARNESS, not the protocol.  This package
makes the harness as fault-tolerant as the protocol it drives:

  - :mod:`resilience.store` — generation-rotated ``.npz`` checkpoints
    whose payload carries a content checksum; load falls back to the
    newest INTACT generation when the latest is truncated or bit-
    flipped, and old single-file ``utils/checkpoint`` files still load.
  - :mod:`resilience.supervisor` — drives ``swim.run``,
    ``swim.run_traced`` and ``chaos.monitor.run_monitored`` in
    checkpointed segments with bounded exponential-backoff retry
    around transient failures, and appends gap-free, duplicate-free
    per-segment telemetry to a resumable JSONL journal (round-cursor
    dedup; trace-first / checkpoint-second write order).
  - :mod:`resilience.harness` — a subprocess driver that SIGKILLs the
    run at a seeded random round + write-stage and relaunches it,
    asserting the resumed final state is bit-identical to an
    uninterrupted run and the merged telemetry is complete.

Entry points: ``bench.py --resilience [--smoke]`` and
``experiments/resilience_drill.py``.
"""

from scalecube_cluster_tpu.resilience.store import (  # noqa: F401
    CheckpointCorruptError,
    CheckpointExhaustedError,
    CheckpointStore,
)
from scalecube_cluster_tpu.resilience.supervisor import (  # noqa: F401
    KillPlan,
    RetryPolicy,
    RunShape,
    run_resilient,
)
