"""Generation-rotated, checksummed checkpoint store.

``utils/checkpoint`` writes ONE ``.npz`` per run: atomic against a kill
mid-write, but a single bad byte in that file (a torn filesystem, a
flaky NFS mount, cosmic-ray bit rot — all real on preemptible fleets)
strands the whole run.  This store keeps the last ``keep`` GENERATIONS
(``<base>.gen-<next_round>.npz``), garbage-collecting older ones, and
every payload carries a sha256 content checksum computed over the
arrays themselves:

  - :meth:`CheckpointStore.save` — atomic write of the new generation,
    then GC (write-first, delete-second: the store never holds fewer
    intact generations than before the call).
  - :meth:`CheckpointStore.load_latest` — newest-first scan.  A
    candidate that fails to open (truncated zip), fails its CRC, lacks
    required members, or fails the content checksum is recorded and
    skipped; the newest INTACT generation wins.  When every candidate
    is corrupt the error names each one tried and why it was rejected.
  - Legacy single-file checkpoints (the plain ``<base>`` path written
    by ``utils/checkpoint.save``) still load: the bare file is the
    final fallback candidate, accepted without a checksum (it predates
    the format) — MIGRATING.md has the note.

The payload is a flat ``{name: np.ndarray}`` dict plus the cursor
(``next_round``), the PRNG key and a JSON meta blob — the same layout
``utils/checkpoint`` uses (``state/<field>`` keys for the SwimState),
extended by the supervisor with ``telemetry/``- and ``monitor/``-
prefixed aux arrays per run shape.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from typing import List, Optional, Tuple

import numpy as np

from scalecube_cluster_tpu.utils import checkpoint as ckpt

CHECKSUM_KEY = "__checksum_sha256__"
_GEN_RE_TMPL = r"\.gen-(\d{8,})\.npz$"


class CheckpointCorruptError(RuntimeError):
    """One candidate failed verification (internal; callers normally see
    only :class:`CheckpointExhaustedError` after every fallback fails)."""


class CheckpointExhaustedError(RuntimeError):
    """No intact generation left.  ``candidates`` is the ordered list of
    (path, reason-rejected) pairs — every file tried, newest first."""

    def __init__(self, base_path: str, candidates: List[Tuple[str, str]]):
        self.candidates = candidates
        lines = "\n".join(f"  - {p}: {why}" for p, why in candidates)
        super().__init__(
            f"no intact checkpoint generation for {base_path!r}; "
            f"tried {len(candidates)} candidate(s):\n{lines}\n"
            f"restore a generation or delete the lineage to start over"
        )


def payload_checksum(arrays: dict) -> str:
    """sha256 hex over the payload arrays (sorted name order; name,
    dtype, shape and raw bytes all covered).  The checksum array itself
    is excluded, so verification recomputes exactly this."""
    h = hashlib.sha256()
    for name in sorted(arrays):
        if name == CHECKSUM_KEY:
            continue
        a = np.ascontiguousarray(np.asarray(arrays[name]))
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(repr(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


class CheckpointStore:
    """Rotated + checksummed checkpoint lineage at ``base_path``
    (module docstring).  ``keep`` >= 1 generations are retained."""

    def __init__(self, base_path: str, keep: int = 3):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.base_path = base_path
        self.keep = keep
        self._gen_re = re.compile(
            re.escape(os.path.basename(base_path)) + _GEN_RE_TMPL
        )

    # -- paths -------------------------------------------------------------

    def gen_path(self, generation: int) -> str:
        return f"{self.base_path}.gen-{generation:08d}.npz"

    def generations_on_disk(self) -> List[int]:
        """Sorted (ascending) generation cursors present next to base."""
        directory = os.path.dirname(os.path.abspath(self.base_path)) or "."
        if not os.path.isdir(directory):
            return []
        gens = []
        for fn in os.listdir(directory):
            m = self._gen_re.match(fn)
            if m:
                gens.append(int(m.group(1)))
        return sorted(gens)

    # -- write -------------------------------------------------------------

    def save(self, arrays: dict, next_round: int, key=None,
             meta: Optional[dict] = None) -> str:
        """Write generation ``next_round`` atomically, then GC older
        generations past ``keep``.  Returns the path written.

        GC runs strictly AFTER the new generation is durable (write-
        first, delete-second), so a kill anywhere in this method leaves
        at least as many intact generations as before it started.

        GC considers only generations strictly OLDER than the one just
        written — never the new file itself, and never a NEWER one.
        Newer generations can only exist after load_latest fell back
        past corrupt ones (the cursor moved backwards); blindly keeping
        "the newest keep by number" would then delete the just-written
        and the intact older generations in favor of the corrupt ones,
        exhausting the lineage.  Left alone, the corrupt stragglers age
        out of the window once the cursor passes them again.
        """
        payload = {k: np.asarray(v) for k, v in arrays.items()}
        payload["next_round"] = np.int64(next_round)
        if key is not None:
            import jax

            payload["key_data"] = np.asarray(jax.random.key_data(key))
        payload["meta_json"] = np.frombuffer(
            json.dumps(meta or {}).encode(), dtype=np.uint8
        )
        digest = payload_checksum(payload)
        payload[CHECKSUM_KEY] = np.frombuffer(digest.encode(),
                                              dtype=np.uint8)
        path = self.gen_path(next_round)
        ckpt._atomic_savez(path, payload)
        older = [g for g in self.generations_on_disk() if g < next_round]
        keep_older = self.keep - 1      # the new generation counts
        for gen in (older[:-keep_older] if keep_older else older):
            try:
                os.unlink(self.gen_path(gen))
            except FileNotFoundError:  # concurrent GC — already gone
                pass
        return path

    # -- read --------------------------------------------------------------

    def _load_candidate(self, path: str, checksummed: bool = True) -> tuple:
        """(arrays, next_round, key, meta) of one verified candidate, or
        raise :class:`CheckpointCorruptError` with the reason."""
        try:
            with np.load(path) as z:
                raw = {name: z[name] for name in z.files}
        except Exception as e:  # noqa: BLE001 — any read failure IS
            # corruption for fallback purposes: zipfile raises
            # BadZipFile on truncation, zlib.error / EOFError on
            # damaged streams, OSError on filesystem trouble,
            # ValueError on malformed .npy members — the correct
            # response to all of them is "try the previous generation".
            raise CheckpointCorruptError(
                f"unreadable npz ({type(e).__name__}: {e})"
            ) from e
        if checksummed:
            if CHECKSUM_KEY not in raw:
                raise CheckpointCorruptError("missing content checksum")
            stored = bytes(raw[CHECKSUM_KEY].tobytes()).decode(
                "ascii", "replace"
            )
            actual = payload_checksum(raw)
            if stored != actual:
                raise CheckpointCorruptError(
                    f"content checksum mismatch (stored {stored[:12]}…, "
                    f"recomputed {actual[:12]}…)"
                )
        if "next_round" not in raw or "meta_json" not in raw:
            raise CheckpointCorruptError(
                "payload lacks next_round/meta_json members"
            )
        next_round = int(raw["next_round"])
        key = None
        if "key_data" in raw:
            import jax

            key = jax.random.wrap_key_data(
                jax.numpy.asarray(raw["key_data"])
            )
        meta = json.loads(
            bytes(raw["meta_json"].tobytes()).decode() or "{}"
        )
        arrays = {
            k: v for k, v in raw.items()
            if k not in ("next_round", "key_data", "meta_json",
                         CHECKSUM_KEY)
        }
        return arrays, next_round, key, meta

    def load_latest(self, log=None) -> Optional[tuple]:
        """Newest intact generation, or None when the lineage is empty.

        Returns ``(arrays, next_round, key, meta, info)`` where ``info``
        is ``{"path", "generation", "fallbacks": [(path, reason), ...]}``
        — a non-empty ``fallbacks`` list means newer generations were
        rejected as corrupt (each with its reason).  Raises
        :class:`CheckpointExhaustedError` when candidates exist but none
        verifies.
        """
        rejected: List[Tuple[str, str]] = []
        for gen in reversed(self.generations_on_disk()):
            path = self.gen_path(gen)
            try:
                arrays, next_round, key, meta = self._load_candidate(path)
            except CheckpointCorruptError as e:
                rejected.append((path, str(e)))
                if log is not None:
                    log.warning("checkpoint %s rejected: %s — falling "
                                "back to previous generation", path, e)
                continue
            return arrays, next_round, key, meta, {
                "path": path, "generation": gen, "fallbacks": rejected,
            }
        # Legacy single-file checkpoint (pre-rotation format): accepted
        # without a checksum — it predates the field.
        if os.path.exists(self.base_path):
            try:
                arrays, next_round, key, meta = self._load_candidate(
                    self.base_path, checksummed=False
                )
            except CheckpointCorruptError as e:
                rejected.append((self.base_path, str(e)))
            else:
                return arrays, next_round, key, meta, {
                    "path": self.base_path, "generation": None,
                    "fallbacks": rejected, "legacy": True,
                }
        if rejected:
            raise CheckpointExhaustedError(self.base_path, rejected)
        return None
