"""Core membership-record semantics: status codes and the merge rule.

This pins the SWIM merge semantics of the reference's
``MembershipRecord.isOverrides`` (reference:
cluster/src/main/java/io/scalecube/cluster/membership/MembershipRecord.java:66-84)
as pure functions, in two forms:

  - scalar Python (used by the event-driven oracle in ``oracle/``),
  - vectorized JAX/numpy (used inside the TPU tick in ``models/``).

The truth table of the reference's ``MembershipRecordTest`` is ported
verbatim in ``tests/test_records.py`` and must hold for both forms.

Status encoding
---------------
The reference stores records in a ``Map<id, MembershipRecord>`` where a
missing key means "unknown member" and DEAD records are *removed* from the
table on acceptance (MembershipProtocolImpl.java:512-513).  The dense
``[N, N]`` table therefore needs a fourth code for "no record":

  ALIVE=0, SUSPECT=1, DEAD=2 match the reference enum order
  (membership/MemberStatus.java:3-16); ABSENT=3 encodes the null record.

Two storage conventions exist for accepted DEAD records, one per layer:

  - ``apply_record`` (oracle / row-merge path): an accepted DEAD maps to
    ABSENT immediately — the table only ever holds ALIVE/SUSPECT/ABSENT,
    exactly like the reference's map.
  - ``ops/delivery.merge_inbox`` (dense tick): the DEAD code + incarnation
    stay in the table so the death notice keeps gossiping for its remaining
    spread window; for merge *gating* a stored DEAD behaves like ABSENT,
    and transmission masks keep it off SYNC payloads.  See the
    merge_inbox docstring for the argument.

``is_overrides`` handles all four codes so the same function gates both
message merges and SYNC row merges.
"""

from __future__ import annotations

import enum
from typing import Tuple

import jax.numpy as jnp


class MemberStatus(enum.IntEnum):
    """Member liveness status (reference: membership/MemberStatus.java:3-16).

    ABSENT is this implementation's encoding of "no record in the table"
    (the reference's ``null``); it never appears on the wire.
    """

    ALIVE = 0
    SUSPECT = 1
    DEAD = 2
    ABSENT = 3


ALIVE = int(MemberStatus.ALIVE)
SUSPECT = int(MemberStatus.SUSPECT)
DEAD = int(MemberStatus.DEAD)
ABSENT = int(MemberStatus.ABSENT)


def is_overrides(new_status: int, new_inc: int, old_status: int, old_inc: int) -> bool:
    """Does record (new_status, new_inc) override table entry (old_status, old_inc)?

    Exact port of MembershipRecord.isOverrides (MembershipRecord.java:66-84):

      1. no existing record (ABSENT) -> accept only ALIVE;
      2. existing DEAD              -> nothing overrides;
      3. new DEAD                   -> always overrides;
      4. equal incarnation          -> only SUSPECT beats ALIVE;
      5. otherwise                  -> higher incarnation wins.
    """
    if old_status == ABSENT:
        return new_status == ALIVE
    if old_status == DEAD:
        return False
    if new_status == DEAD:
        return True
    if new_status == ABSENT:
        return False
    if new_inc == old_inc:
        return new_status != old_status and new_status == SUSPECT
    return new_inc > old_inc


def is_overrides_array(new_status, new_inc, old_status, old_inc):
    """Vectorized ``is_overrides`` over arrays of status/incarnation codes.

    Branch-free formulation of MembershipRecord.java:66-84 — all five rules
    composed with ``where``-style selects so it lowers to elementwise VPU ops
    under jit.  Works on any broadcastable shapes.
    """
    new_status = jnp.asarray(new_status)
    old_status = jnp.asarray(old_status)
    new_inc = jnp.asarray(new_inc)
    old_inc = jnp.asarray(old_inc)

    # Rule 4/5: live-vs-live comparison.
    equal_inc = new_inc == old_inc
    suspect_beats_alive = (new_status != old_status) & (new_status == SUSPECT)
    live_wins = jnp.where(equal_inc, suspect_beats_alive, new_inc > old_inc)

    result = live_wins
    # Rule 3: new DEAD always overrides a live record.
    result = jnp.where(new_status == DEAD, True, result)
    # New ABSENT is not a record; it never overrides.
    result = jnp.where(new_status == ABSENT, False, result)
    # Rule 2: existing DEAD is terminal.
    result = jnp.where(old_status == DEAD, False, result)
    # Rule 1: no existing record -> accept only ALIVE.
    result = jnp.where(old_status == ABSENT, new_status == ALIVE, result)
    return result


def merge_key(status, inc):
    """Total-order key for folding many inbound records about one subject.

    Within one simulation round a node can receive several records about the
    same subject (FD verdict, gossip, SYNC).  The reference serializes them
    through one scheduler thread in arrival order
    (MembershipProtocolImpl.java:475-541); arrival order is arbitrary, so any
    deterministic serialization is a faithful schedule.  We pick the one
    induced by this key: the fold keeps the record with the largest

        key = (is_dead << 30) | (min(incarnation, 2^29 - 1) << 1) | is_suspect

    i.e. DEAD absorbs everything (rule 3), then higher incarnation wins
    (rule 5), then SUSPECT beats ALIVE at equal incarnation (rule 4).  This
    max is associative/commutative, so a segment/matmul reduce over inbound
    records is schedule-deterministic.  ABSENT maps to key -1 (never wins).

    The incarnation field saturates at 2^29 - 1 so the DEAD flag can never
    be overtaken in int32 (incarnations only grow by refutation bumps, so
    half a billion is unreachable in any realistic run; saturation degrades
    the order among such records instead of silently corrupting rule 3).
    """
    status = jnp.asarray(status)
    inc = jnp.asarray(inc, dtype=jnp.int32)
    is_dead = (status == DEAD).astype(jnp.int32)
    is_suspect = (status == SUSPECT).astype(jnp.int32)
    # int32 layout: bit 30 = dead flag, bits 1..29 = incarnation, bit 0 = suspect.
    inc_sat = jnp.minimum(inc, jnp.int32(2**29 - 1))
    key = (is_dead << 30) | (inc_sat << 1) | is_suspect
    return jnp.where(status == ABSENT, jnp.int32(-1), key)


def merge_key16(status, inc):
    """int16 variant of :func:`merge_key` — the capacity-oriented wire
    format (models/swim.SwimParams.compact_carry).

    Layout: bit 14 = dead flag, bits 1..13 = incarnation (saturating at
    2^13 - 1 = 8191), bit 0 = suspect; ABSENT -> -1.  Same lattice order
    as the int32 key — DEAD absorbs, then incarnation, then SUSPECT at
    equal incarnation — at half the wire/table bytes.  Incarnations only
    grow by refutation bumps (one per false suspicion or revival of the
    same member), so 8k is far past any realistic run; saturation
    degrades order among such records instead of corrupting the DEAD
    rule, exactly like the int32 key's 2^29 cap.
    """
    status = jnp.asarray(status)
    inc = jnp.asarray(inc, dtype=jnp.int32)
    is_dead = (status == DEAD).astype(jnp.int32)
    is_suspect = (status == SUSPECT).astype(jnp.int32)
    inc_sat = jnp.minimum(inc, jnp.int32(2**13 - 1))
    key = (is_dead << 14) | (inc_sat << 1) | is_suspect
    return jnp.where(status == ABSENT, -1, key).astype(jnp.int16)


def apply_record(old_status, old_inc, new_status, new_inc):
    """Merge one inbound record into a table entry; returns (status, inc).

    The acceptance gate is ``is_overrides_array``; on acceptance a DEAD
    record *removes* the entry (becomes ABSENT), matching
    MembershipProtocolImpl.java:512-516 where accepted DEAD records are
    deleted from the membership table rather than stored.  (The dense tick's
    ``ops/delivery.merge_inbox`` deliberately deviates — it stores the DEAD
    code so the tombstone keeps spreading; see the module docstring.)
    """
    accept = is_overrides_array(new_status, new_inc, old_status, old_inc)
    stored_status = jnp.where(new_status == DEAD, ABSENT, new_status)
    status = jnp.where(accept, stored_status, old_status)
    inc = jnp.where(accept, new_inc, old_inc)
    return status.astype(jnp.int8), inc.astype(jnp.int32)


def fold_records(statuses, incs, axis: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Reduce many records about the same subject to the schedule winner.

    ``statuses``/``incs`` have a fold axis of candidate records; returns the
    (status, inc) with the maximal ``merge_key`` along that axis.  Use with
    ABSENT padding for "no message".
    """
    keys = merge_key(statuses, incs)
    idx = jnp.argmax(keys, axis=axis)
    win_status = jnp.take_along_axis(
        jnp.asarray(statuses), jnp.expand_dims(idx, axis), axis=axis
    ).squeeze(axis)
    win_inc = jnp.take_along_axis(
        jnp.asarray(incs), jnp.expand_dims(idx, axis), axis=axis
    ).squeeze(axis)
    return win_status, win_inc


def merge_inbound(entry_status, entry_inc, statuses, incs, axis: int):
    """Merge a round's worth of inbound records into a table entry.

    Equivalent to *one valid arrival-order serialization* of the reference's
    per-message ``updateMembership`` loop (MembershipProtocolImpl.java:475-541)
    — specifically: for an ABSENT entry, the best ALIVE record is applied
    first (only ALIVE opens the null gate, MembershipRecord.java:67-69), then
    the remaining records in ascending ``merge_key`` order, ending with the
    global winner.  Because post-gate application is monotone in the key,
    that whole suffix collapses to applying just the winner.

    Returns the merged (status int8, inc int32), reduced over ``axis``.
    """
    entry_status = jnp.asarray(entry_status)
    entry_inc = jnp.asarray(entry_inc)
    statuses = jnp.asarray(statuses)
    incs = jnp.asarray(incs)

    win_status, win_inc = fold_records(statuses, incs, axis)

    # Best ALIVE record (for opening the null gate on ABSENT entries).
    alive_keys = jnp.where(statuses == ALIVE, merge_key(statuses, incs), jnp.int32(-1))
    alive_idx = jnp.argmax(alive_keys, axis=axis)
    any_alive = jnp.max(alive_keys, axis=axis) >= 0
    best_alive_inc = jnp.take_along_axis(
        incs, jnp.expand_dims(alive_idx, axis), axis=axis
    ).squeeze(axis)

    open_gate = (entry_status == ABSENT) & any_alive
    gate_status = jnp.where(open_gate, ALIVE, entry_status)
    gate_inc = jnp.where(open_gate, best_alive_inc, entry_inc)

    return apply_record(gate_status, gate_inc, win_status, win_inc)
